"""AI service provider SPI — the north-star extension point.

Equivalent of the reference's ``ServiceProvider`` SPI
(``langstream-agents/langstream-ai-agents/src/main/java/com/datastax/oss/streaming/ai/services/ServiceProvider.java:24``,
``completions/CompletionsService.java:22-35``, ``embeddings/EmbeddingsService.java:24``):
a provider resolves a completions service and an embeddings service from a
``resources:`` config block. The reference's providers call OpenAI / VertexAI /
Bedrock / HuggingFace over HTTPS; this framework's flagship provider is
``jax_local`` — the model runs *in-process* on the TPU attached to the agent.

Streaming contract: ``get_chat_completions`` takes an optional
``StreamingChunksConsumer``; chunks are delivered as they decode, with the
reference's exponential chunk batching (1, 2, 4, ... up to
``min-chunks-per-message``; ``OpenAICompletionService.java:126,290-300``)
applied by the *caller* side (the chat-completions step), so services emit
raw deltas.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class ChatMessage:
    """One chat turn (role + content)."""

    role: str
    content: str

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "ChatMessage":
        return cls(role=config.get("role", "user"), content=config.get("content", ""))


@dataclasses.dataclass
class ChatChunk:
    """One streamed delta of a completion."""

    content: str
    index: int = 0
    is_last: bool = False


@dataclasses.dataclass
class ChatCompletionResult:
    """Final result of a (possibly streamed) completion."""

    content: str
    role: str = "assistant"
    finish_reason: str = "stop"
    prompt_tokens: int = 0
    completion_tokens: int = 0
    # per-token text pieces + log-probabilities (OpenAI-style logprobs;
    # filled by providers that expose them — notably jax-local, whose
    # engine samples them in-jit). Consumed by the flare-controller
    # (reference: FlareControllerAgent.java tokens/logprobs fields).
    tokens: Optional[List[str]] = None
    logprobs: Optional[List[float]] = None
    # per-token top-K alternatives (OpenAI `top_logprobs`): one list of
    # (token text, logprob) pairs per generated token. Needs the
    # jax-local engine's `logprobs-top-k` config > 0.
    top_logprobs: Optional[List[List[tuple]]] = None


class StreamingChunksConsumer(abc.ABC):
    """Receives streamed chunks (``CompletionsService.StreamingChunksConsumer``,
    ``CompletionsService.java:29-35``)."""

    @abc.abstractmethod
    def consume_chunk(self, answer_id: str, index: int, chunk: ChatChunk, last: bool) -> None:
        ...


class CompletionsService(abc.ABC):
    """Chat + text completions (``CompletionsService.java:22``)."""

    # max top_logprobs alternatives this service can return per token
    # (0 = unsupported). Implementations that support the feature set
    # it; the OpenAI HTTP layer validates requests against it.
    top_logprobs_limit: int = 0

    @abc.abstractmethod
    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        ...

    async def get_text_completions(
        self,
        prompt: List[str],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        """Default: treat the prompt as a single user message."""
        messages = [ChatMessage("user", p) for p in prompt]
        return await self.get_chat_completions(messages, options, stream_consumer)

    async def close(self) -> None:
        ...


class EmbeddingsService(abc.ABC):
    """Batch text → vectors (``EmbeddingsService.java:24``).

    Batched by contract: the runtime's ordered async batch executor
    coalesces records into one call so the TPU sees one padded matmul batch.
    """

    @abc.abstractmethod
    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        ...

    async def close(self) -> None:
        ...


class ServiceProvider(abc.ABC):
    """Resolves services from a resource config (``ServiceProvider.java:24``)."""

    name: str = ""

    @abc.abstractmethod
    def supports(self, resource_config: Dict[str, Any]) -> bool:
        """True when this provider owns the given ``resources:`` entry
        (the reference keys on which config section is present, e.g.
        ``open-ai:`` vs ``vertex-ai:`` — ours keys on ``jax-local:`` etc.)."""

    @abc.abstractmethod
    def get_completions_service(
        self, resource_config: Dict[str, Any]
    ) -> CompletionsService:
        ...

    @abc.abstractmethod
    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        ...

    async def close(self) -> None:
        ...
