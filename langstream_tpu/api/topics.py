"""Broker-portable topic SPI.

Equivalent of the reference's topic contracts
(``langstream-api/src/main/java/ai/langstream/api/runner/topics/TopicConnectionsRuntime.java:23``,
``TopicConsumer.java:22``, ``TopicProducer.java:22``, ``TopicReader.java:18``,
``TopicAdmin.java:18``, ``TopicOffsetPosition.java``): consumers join a group
and share partitions; producers write; readers tail a topic without a group
(the gateway uses them); admin creates/deletes topics.

All data methods are coroutines (see ``api.agent`` module docstring for the
asyncio-first rationale).
"""

from __future__ import annotations

import abc
import dataclasses
import enum
from typing import Any, Dict, List, Optional

from langstream_tpu.api.records import Record


class OffsetPosition(enum.Enum):
    """Where a reader starts (``TopicOffsetPosition.java``)."""

    EARLIEST = "earliest"
    LATEST = "latest"


@dataclasses.dataclass(frozen=True)
class TopicSpec:
    """Planner-level topic description (``model/TopicDefinition.java:30``)."""

    name: str
    partitions: int = 1
    creation_mode: str = "create-if-not-exists"  # or "none"
    deletion_mode: str = "none"  # or "delete"
    options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    implicit: bool = False
    # declared value schema ({type: avro, schema: "<json>"}) — flows to
    # schema-aware producers (Kafka + registry → Confluent framing)
    schema: Optional[Dict[str, Any]] = None


class TopicProducer(abc.ABC):
    @abc.abstractmethod
    async def write(self, record: Record) -> None:
        """Durably publish one record (await = broker ack)."""

    async def start(self) -> None:
        ...

    async def close(self) -> None:
        ...

    @property
    def topic(self) -> str:
        raise NotImplementedError

    def total_in(self) -> int:
        """Records written so far (metrics parity with the reference's
        producer counters)."""
        return 0


class TopicConsumer(abc.ABC):
    @abc.abstractmethod
    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        """Poll the next batch for this group member."""

    @abc.abstractmethod
    async def commit(self, records: List[Record]) -> None:
        """Acknowledge ``records``. Out-of-order acks are allowed; the
        implementation must only advance the durable offset up to the
        contiguous watermark (reference:
        ``langstream-kafka-runtime/.../KafkaConsumerWrapper.java:52-230``)."""

    async def start(self) -> None:
        ...

    async def close(self) -> None:
        ...

    def total_out(self) -> int:
        return 0


class TopicReader(abc.ABC):
    """Group-less tailing reader (gateway consume path,
    ``TopicReader.java:18``)."""

    @abc.abstractmethod
    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        ...

    async def start(self) -> None:
        ...

    async def close(self) -> None:
        ...


class TopicAdmin(abc.ABC):
    @abc.abstractmethod
    async def create_topic(self, spec: TopicSpec) -> None:
        ...

    @abc.abstractmethod
    async def delete_topic(self, name: str) -> None:
        ...

    async def close(self) -> None:
        ...


class TopicConnectionsRuntime(abc.ABC):
    """Factory for consumers/producers/readers/admin against one broker
    (``TopicConnectionsRuntime.java:23-36``)."""

    @abc.abstractmethod
    def create_consumer(
        self,
        agent_id: str,
        config: Dict[str, Any],
    ) -> TopicConsumer:
        """``config`` carries at least ``topic`` and ``group``."""

    @abc.abstractmethod
    def create_producer(
        self,
        agent_id: str,
        config: Dict[str, Any],
    ) -> TopicProducer:
        ...

    @abc.abstractmethod
    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        ...

    @abc.abstractmethod
    def create_admin(self) -> TopicAdmin:
        ...

    def create_deadletter_producer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> Optional[TopicProducer]:
        """Producer for ``<topic>-deadletter`` (reference:
        ``KafkaTopicConnectionsRuntime.createDeadletterTopicProducer``);
        None when the runtime has no dead-letter support."""
        topic = config.get("topic")
        if not topic:
            return None
        return self.create_producer(agent_id, {**config, "topic": f"{topic}-deadletter"})

    async def init(self, streaming_cluster_config: Dict[str, Any]) -> None:
        ...

    async def close(self) -> None:
        ...
