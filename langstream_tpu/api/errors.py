"""Error-handling policy for record processing.

Equivalent of the reference's ``ErrorsSpec``
(``langstream-api/src/main/java/ai/langstream/api/model/ErrorsSpec.java:26``)
and ``StandardErrorsHandler``
(``langstream-runtime/langstream-runtime-impl/src/main/java/ai/langstream/runtime/agent/errors/StandardErrorsHandler.java:28``):
each agent declares ``on-failure`` (fail | skip | dead-letter) and ``retries``;
pipeline-level defaults flow into agents that don't override them
(``ErrorsSpec.withDefaultsFrom``, lines 24-31).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Optional


class UnavailableError(RuntimeError):
    """Transient serving unavailability the CALLER should retry: the
    request was never started (no partial work), and ``retry_after_s``
    estimates when capacity returns. The OpenAI surface maps this to
    HTTP 503 + ``Retry-After`` — the contract that turns an engine
    rebuild or an overloaded queue into a bounded, retryable signal
    instead of a 500 (DeepServe's fast-failure property)."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))


class QueueTimeoutError(UnavailableError):
    """A pending request exceeded its admission deadline and was shed
    before ever holding a slot (load shedding — the alternative is
    waiting in the engine queue forever while the caller times out
    anyway). ``retry_after_s`` derives from the current queue depth and
    the engine's EWMA step time."""


class EngineRebuildingError(UnavailableError):
    """The engine supervisor is tearing down / rebuilding a crashed
    engine; in-flight sessions are being resurrected and NEW work must
    retry after the rebuild window."""


class FatalAgentError(RuntimeError):
    """Errors the record-level policy must NEVER consume: the agent
    cannot make progress (dead child process, poisoned device state),
    so retry/skip/dead-letter would silently drop every subsequent
    record. The runner re-raises these fatally so the pod restarts —
    the analogue of the reference's JVM-exit main error handler
    (``AgentRunner.java:87-91`` ``mainErrorHandler``)."""


class FailureAction(enum.Enum):
    FAIL = "fail"
    SKIP = "skip"
    DEAD_LETTER = "dead-letter"


@dataclasses.dataclass(frozen=True)
class ErrorsSpec:
    """``retries`` + ``on-failure`` with defaults inheritance."""

    retries: Optional[int] = None
    on_failure: Optional[str] = None

    DEFAULT_RETRIES = 0
    DEFAULT_ON_FAILURE = FailureAction.FAIL

    @classmethod
    def from_config(cls, config: Optional[Dict[str, Any]]) -> "ErrorsSpec":
        if not config:
            return cls()
        return cls(
            retries=config.get("retries"),
            on_failure=config.get("on-failure", config.get("on_failure")),
        )

    def with_defaults_from(self, defaults: "ErrorsSpec") -> "ErrorsSpec":
        """Fill unset fields from pipeline defaults
        (``ErrorsSpec.withDefaultsFrom``, ``ErrorsSpec.java:24-31``)."""
        return ErrorsSpec(
            retries=self.retries if self.retries is not None else defaults.retries,
            on_failure=(
                self.on_failure if self.on_failure is not None else defaults.on_failure
            ),
        )

    def resolved_retries(self) -> int:
        return self.retries if self.retries is not None else self.DEFAULT_RETRIES

    def resolved_action(self) -> FailureAction:
        if self.on_failure is None:
            return self.DEFAULT_ON_FAILURE
        return FailureAction(self.on_failure)

    def to_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.retries is not None:
            out["retries"] = self.retries
        if self.on_failure is not None:
            out["on-failure"] = self.on_failure
        return out


class ErrorHandlingDecision(enum.Enum):
    """What the runner should do after a record failure
    (reference: ``StandardErrorsHandler.ErrorsProcessingOutcome``)."""

    RETRY = "retry"
    SKIP = "skip"
    FAIL = "fail"
    DEAD_LETTER = "dead-letter"


class StandardErrorsHandler:
    """Counts failures per record attempt and decides retry/skip/fail/DLQ.

    Mirrors ``StandardErrorsHandler.java:28``: a record may be retried
    ``retries`` times; once exhausted, the action is ``on-failure``
    (dead-letter falls back to fail when no dead-letter producer exists —
    the runner handles that downgrade).
    """

    def __init__(self, spec: ErrorsSpec) -> None:
        self.spec = spec
        self.failures = 0

    def handle_error(self, attempts_for_record: int) -> ErrorHandlingDecision:
        self.failures += 1
        if attempts_for_record <= self.spec.resolved_retries():
            return ErrorHandlingDecision.RETRY
        action = self.spec.resolved_action()
        if action is FailureAction.SKIP:
            return ErrorHandlingDecision.SKIP
        if action is FailureAction.DEAD_LETTER:
            return ErrorHandlingDecision.DEAD_LETTER
        return ErrorHandlingDecision.FAIL
