"""Asset manager SPI: provision tables/collections/indexes at setup.

Equivalent of the reference's AssetManager SPI
(``langstream-api/src/main/java/ai/langstream/api/runner/assets/AssetManager.java``
with providers in ``langstream-core/.../impl/assets/`` — Cassandra, JDBC,
Milvus, OpenSearch, Solr) and its registry
(``AssetManagerRegistry.java``). Assets declare what infrastructure a
pipeline needs; the setup phase creates them according to
``creation-mode`` and tears them down per ``deletion-mode``.

Built-in managers cover the TPU build's local datasources (SQL tables
via the sqlite/jdbc datasource, vector collections via the in-process
vector store); external systems (Cassandra/Milvus/...) plug in through
:func:`register_asset_manager`.
"""

from __future__ import annotations

import abc
import logging
from typing import Any, Callable, Dict, Optional

from langstream_tpu.model.application import AssetDefinition

logger = logging.getLogger(__name__)


class AssetManager(abc.ABC):
    """Lifecycle of one asset instance
    (reference: ``AssetManager.java`` — init/assetExists/deployAsset/
    deleteAssetIfExists/close)."""

    async def init(
        self, asset: AssetDefinition, resources: Dict[str, Any]
    ) -> None:
        self.asset = asset
        self.resources = resources

    @abc.abstractmethod
    async def asset_exists(self) -> bool: ...

    @abc.abstractmethod
    async def deploy_asset(self) -> None: ...

    async def delete_asset(self) -> bool:
        return False

    async def close(self) -> None:
        pass


_MANAGERS: Dict[str, Callable[[], AssetManager]] = {}


def register_asset_manager(
    asset_type: str, factory: Callable[[], AssetManager]
) -> None:
    _MANAGERS[asset_type] = factory


def asset_manager_types() -> list:
    _ensure_builtin()
    return sorted(_MANAGERS)


def create_asset_manager(asset_type: str) -> AssetManager:
    _ensure_builtin()
    factory = _MANAGERS.get(asset_type)
    if factory is None:
        raise ValueError(
            f"no asset manager for type {asset_type!r} "
            f"(available: {sorted(_MANAGERS)})"
        )
    return factory()


_builtin = False


def _ensure_builtin() -> None:
    global _builtin
    if _builtin:
        return
    _builtin = True
    from langstream_tpu.runtime import assets as _impl  # noqa: F401


async def deploy_assets(
    assets, resources: Dict[str, Any]
) -> None:
    """Setup-phase provisioning (reference:
    ``ApplicationSetupRunner`` asset deployment)."""
    for asset in assets:
        if asset.creation_mode != "create-if-not-exists":
            continue
        manager = create_asset_manager(asset.asset_type)
        await manager.init(asset, resources)
        try:
            if await manager.asset_exists():
                logger.info("asset %s already exists", asset.name)
                continue
            await manager.deploy_asset()
            logger.info("created asset %s (%s)", asset.name, asset.asset_type)
        finally:
            await manager.close()


async def cleanup_assets(assets, resources: Dict[str, Any]) -> None:
    for asset in assets:
        if asset.deletion_mode != "delete":
            continue
        manager = create_asset_manager(asset.asset_type)
        await manager.init(asset, resources)
        try:
            if await manager.delete_asset():
                logger.info("deleted asset %s", asset.name)
        finally:
            await manager.close()
