"""Minimal metrics SPI.

Equivalent of the reference's counter-only reporter
(``langstream-api/src/main/java/ai/langstream/api/runner/code/MetricsReporter.java:18``)
with a Prometheus-backed implementation provided by the runtime
(reference impl: ``langstream-runtime-impl/.../metrics/PrometheusMetricsReporter.java``).
"""

from __future__ import annotations

import threading
from typing import Dict


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def count(self, amount: int = 1) -> None:
        with self._lock:
            self._value += amount

    def value(self) -> int:
        return self._value


class MetricsReporter:
    """Namespaced counter registry; ``with_prefix`` mirrors the reference's
    ``MetricsReporter.withPodName/withAgentName`` chaining."""

    def __init__(self, prefix: str = "") -> None:
        self.prefix = prefix
        self._counters: Dict[str, Counter] = {}
        self._lock = threading.Lock()

    def with_prefix(self, prefix: str) -> "MetricsReporter":
        child = MetricsReporter(
            f"{self.prefix}_{prefix}" if self.prefix else prefix
        )
        child._counters = self._counters  # shared registry
        child._lock = self._lock
        return child

    def counter(self, name: str) -> Counter:
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            counter = self._counters.get(full)
            if counter is None:
                counter = Counter(full)
                self._counters[full] = counter
            return counter

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {name: c.value() for name, c in self._counters.items()}


DISABLED = MetricsReporter()
