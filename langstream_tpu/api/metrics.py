"""Minimal metrics SPI + the ONE Prometheus text renderer.

Equivalent of the reference's counter-only reporter
(``langstream-api/src/main/java/ai/langstream/api/runner/code/MetricsReporter.java:18``)
with a Prometheus-backed implementation provided by the runtime
(reference impl: ``langstream-runtime-impl/.../metrics/PrometheusMetricsReporter.java``).

:func:`prometheus_text` is the single registry→classic-exposition path
(counters, gauges, cumulative-``le`` histograms, ``# HELP``/``# TYPE``)
shared by every scrape surface — runner pods (``runtime/pod.py``), the
OpenAI server (``serving/openai_api.py``), and the gateway
(``gateway/server.py``) — so the formats cannot drift between them.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Mapping, Optional, Tuple


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        # increments serialize under the lock; value() reads lock-free
        # (a scrape observing a count one tick late is correct
        # Prometheus semantics)
        self._value = 0  # guarded-by: _lock (writes)
        self._lock = threading.Lock()

    def count(self, amount: int = 1) -> None:
        with self._lock:
            self._value += amount

    def value(self) -> int:
        return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus classic shape: cumulative
    ``le`` buckets + sum + count). Default buckets suit latencies in
    seconds from sub-millisecond to minutes."""

    DEFAULT_BUCKETS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    )

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets=None) -> None:
        self.name = name
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        index = len(self.buckets)
        for i, upper in enumerate(self.buckets):
            if value <= upper:
                index = i
                break
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Dict[str, float]:
        """Cumulative bucket counts keyed by ``le`` plus sum/count."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        out: Dict[str, float] = {}
        running = 0
        for upper, count in zip(self.buckets, counts):
            running += count
            out[f"{upper}"] = running
        out["+Inf"] = running + counts[-1]
        out["sum"] = total_sum
        out["count"] = total_count
        return out


class MetricsReporter:
    """Namespaced counter/histogram registry; ``with_prefix`` mirrors the
    reference's ``MetricsReporter.withPodName/withAgentName`` chaining."""

    def __init__(self, prefix: str = "") -> None:
        self.prefix = prefix
        # registry dicts: inserts hold the lock (get-or-create races
        # must not lose a counter); with_prefix SHARES the dicts with
        # the child reporter by reference, which is a lock-free read
        self._counters: Dict[str, Counter] = {}  # guarded-by: _lock (writes)
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: _lock (writes)
        self._lock = threading.Lock()

    def with_prefix(self, prefix: str) -> "MetricsReporter":
        child = MetricsReporter(
            f"{self.prefix}_{prefix}" if self.prefix else prefix
        )
        child._counters = self._counters  # shared registry
        child._histograms = self._histograms
        child._lock = self._lock
        return child

    def counter(self, name: str) -> Counter:
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            counter = self._counters.get(full)
            if counter is None:
                counter = Counter(full)
                self._counters[full] = counter
            return counter

    def histogram(self, name: str, buckets=None) -> Histogram:
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            histogram = self._histograms.get(full)
            if histogram is None:
                histogram = Histogram(full, buckets)
                self._histograms[full] = histogram
            return histogram

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {name: c.value() for name, c in self._counters.items()}

    def histogram_snapshots(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            histograms = dict(self._histograms)
        return {name: h.snapshot() for name, h in histograms.items()}


DISABLED = MetricsReporter()


# ---------------------------------------------------------------------- #
# Prometheus classic text exposition (format 0.0.4)
# ---------------------------------------------------------------------- #
_METRIC_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    metric = _METRIC_NAME.sub("_", name)
    if metric and metric[0].isdigit():
        metric = "_" + metric
    return metric


def _split_labels(name: str) -> Tuple[str, str]:
    """Split a registry key of the form ``metric{label="v"}`` into the
    (sanitized) metric name and its label suffix (kept verbatim). Plain
    names pass through with an empty suffix — this is what lets gauge
    maps carry labeled samples (e.g. per-reason wasted-token counters)
    through the one shared renderer."""
    base, brace, labels = name.partition("{")
    return _sanitize(base), (brace + labels) if brace else ""


def prometheus_text(
    counters: Mapping[str, int],
    gauges: Optional[Mapping[str, float]] = None,
    histograms: Optional[Mapping[str, Mapping[str, float]]] = None,
    help_texts: Optional[Mapping[str, str]] = None,
) -> str:
    """Render counters/gauges/histograms in the Prometheus text
    exposition format (histogram snapshots are the ``le``-keyed dicts
    :meth:`Histogram.snapshot` produces). Counter/gauge keys may carry
    inline labels (``name{reason="x"}``); same-family samples share one
    HELP/TYPE header. ``help_texts`` maps raw metric names to their
    ``# HELP`` line; metrics without one get a generic self-describing
    help so the output always parses as a complete family
    (HELP + TYPE + samples)."""

    def help_line(metric: str, raw: str, kind: str) -> str:
        text = (help_texts or {}).get(raw) or f"langstream-tpu {kind}"
        return f"# HELP {metric} {text}"

    def render(samples, kind: str, suffix: str = "") -> None:
        # sort by (parsed family, labels), NOT by raw key: "_" sorts
        # before "{", so raw-key order could interleave foo_bar between
        # foo and foo{...} and split one family into duplicate
        # HELP/TYPE headers (invalid exposition — Prometheus rejects
        # the whole scrape)
        parsed = []
        for name, value in samples.items():
            metric, labels = _split_labels(name)
            if suffix and not metric.endswith(suffix):
                metric += suffix
            parsed.append((metric, labels, name.partition("{")[0], value))
        family = None
        for metric, labels, raw, value in sorted(parsed):
            if metric != family:
                family = metric
                lines.append(help_line(metric, raw, kind))
                lines.append(f"# TYPE {metric} {kind}")
            lines.append(f"{metric}{labels} {value}")

    lines: List[str] = []
    render(counters, "counter", suffix="_total")
    render(gauges or {}, "gauge")
    for name, snapshot in sorted((histograms or {}).items()):
        metric = _sanitize(name)
        lines.append(help_line(metric, name, "histogram"))
        lines.append(f"# TYPE {metric} histogram")
        for le, value in snapshot.items():
            if le in ("sum", "count"):
                continue
            lines.append(f'{metric}_bucket{{le="{le}"}} {int(value)}')
        lines.append(f"{metric}_sum {snapshot.get('sum', 0.0)}")
        lines.append(f"{metric}_count {int(snapshot.get('count', 0))}")
    return "\n".join(lines) + "\n"


_SAMPLE_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[-+]?[0-9.eE+-]+|NaN|[+-]?Inf)$"
)


def parse_prometheus_text(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse classic exposition text into
    ``{metric: [(labels, value), ...]}`` — used by ``langstream-tpu top``
    and the golden-format tests. Raises ValueError on any line that is
    neither a comment nor a well-formed sample (the format assertion)."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        match = _SAMPLE_LINE.match(line)
        if match is None:
            raise ValueError(f"not a Prometheus sample line: {line!r}")
        labels: Dict[str, str] = {}
        if match.group("labels"):
            for pair in match.group("labels").split(","):
                if not pair:
                    continue
                key, _, value = pair.partition("=")
                labels[key.strip()] = value.strip().strip('"')
        out.setdefault(match.group("name"), []).append(
            (labels, float(match.group("value")))
        )
    return out


def quantile_from_buckets(
    samples: List[Tuple[Dict[str, str], float]], quantile: float
) -> Optional[float]:
    """Approximate a quantile from parsed ``_bucket`` samples (cumulative
    ``le`` counts): linear interpolation inside the bucket containing the
    target rank — the standard Prometheus ``histogram_quantile`` shape
    (the first bucket interpolates from 0). A rank landing in the +Inf
    bucket caps at the highest finite bound rather than returning inf."""
    buckets: List[Tuple[float, float]] = []
    total = 0.0
    for labels, value in samples:
        le = labels.get("le")
        if le is None:
            continue
        upper = float("inf") if le == "+Inf" else float(le)
        buckets.append((upper, value))
        total = max(total, value)
    if not buckets or total <= 0:
        return None
    buckets.sort(key=lambda b: b[0])
    rank = quantile * total
    finite = [upper for upper, _ in buckets if upper != float("inf")]
    cap = finite[-1] if finite else None
    lower, below = 0.0, 0.0
    for upper, cumulative in buckets:
        if cumulative >= rank:
            # rank in the +Inf bucket: cap at the highest finite bound
            # (histogram_quantile semantics) rather than returning inf
            if upper == float("inf"):
                return cap
            if cumulative == below:
                return upper
            fraction = (rank - below) / (cumulative - below)
            return lower + (upper - lower) * max(0.0, min(1.0, fraction))
        lower, below = upper, cumulative
    return cap
