"""Minimal metrics SPI.

Equivalent of the reference's counter-only reporter
(``langstream-api/src/main/java/ai/langstream/api/runner/code/MetricsReporter.java:18``)
with a Prometheus-backed implementation provided by the runtime
(reference impl: ``langstream-runtime-impl/.../metrics/PrometheusMetricsReporter.java``).
"""

from __future__ import annotations

import threading
from typing import Dict


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def count(self, amount: int = 1) -> None:
        with self._lock:
            self._value += amount

    def value(self) -> int:
        return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus classic shape: cumulative
    ``le`` buckets + sum + count). Default buckets suit latencies in
    seconds from sub-millisecond to minutes."""

    DEFAULT_BUCKETS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    )

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets=None) -> None:
        self.name = name
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        index = len(self.buckets)
        for i, upper in enumerate(self.buckets):
            if value <= upper:
                index = i
                break
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Dict[str, float]:
        """Cumulative bucket counts keyed by ``le`` plus sum/count."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        out: Dict[str, float] = {}
        running = 0
        for upper, count in zip(self.buckets, counts):
            running += count
            out[f"{upper}"] = running
        out["+Inf"] = running + counts[-1]
        out["sum"] = total_sum
        out["count"] = total_count
        return out


class MetricsReporter:
    """Namespaced counter/histogram registry; ``with_prefix`` mirrors the
    reference's ``MetricsReporter.withPodName/withAgentName`` chaining."""

    def __init__(self, prefix: str = "") -> None:
        self.prefix = prefix
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def with_prefix(self, prefix: str) -> "MetricsReporter":
        child = MetricsReporter(
            f"{self.prefix}_{prefix}" if self.prefix else prefix
        )
        child._counters = self._counters  # shared registry
        child._histograms = self._histograms
        child._lock = self._lock
        return child

    def counter(self, name: str) -> Counter:
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            counter = self._counters.get(full)
            if counter is None:
                counter = Counter(full)
                self._counters[full] = counter
            return counter

    def histogram(self, name: str, buckets=None) -> Histogram:
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            histogram = self._histograms.get(full)
            if histogram is None:
                histogram = Histogram(full, buckets)
                self._histograms[full] = histogram
            return histogram

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {name: c.value() for name, c in self._counters.items()}

    def histogram_snapshots(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            histograms = dict(self._histograms)
        return {name: h.snapshot() for name, h in histograms.items()}


DISABLED = MetricsReporter()
