"""The framework SPI: records, agent contracts, topic contracts, services.

Re-designed equivalent of the reference's ``langstream-api`` module
(``langstream-api/src/main/java/ai/langstream/api``): the contracts every
agent ("op"), broker runtime, and AI service provider implements.
"""

from langstream_tpu.api.records import Record, SimpleRecord, record_from_value
from langstream_tpu.api.agent import (
    Agent,
    AgentContext,
    AgentProcessor,
    AgentService,
    AgentSink,
    AgentSource,
    ComponentType,
    RecordSink,
    SingleRecordProcessor,
    SourceRecordAndResult,
)
from langstream_tpu.api.errors import ErrorsSpec, FailureAction
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConsumer,
    TopicProducer,
    TopicReader,
    TopicConnectionsRuntime,
)
from langstream_tpu.api.service import (
    ChatChunk,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
)

__all__ = [
    "Agent",
    "AgentContext",
    "AgentProcessor",
    "AgentService",
    "AgentSink",
    "AgentSource",
    "ChatChunk",
    "ChatMessage",
    "CompletionsService",
    "ComponentType",
    "EmbeddingsService",
    "ErrorsSpec",
    "FailureAction",
    "OffsetPosition",
    "Record",
    "RecordSink",
    "ServiceProvider",
    "SimpleRecord",
    "SingleRecordProcessor",
    "SourceRecordAndResult",
    "TopicAdmin",
    "TopicConsumer",
    "TopicProducer",
    "TopicReader",
    "TopicConnectionsRuntime",
    "record_from_value",
]
