"""OpenAI-compatible HTTP serving surface over any CompletionsService.

The reference *consumes* the OpenAI API (``OpenAICompletionService.java``);
this module also *serves* it, so existing OpenAI clients (SDKs, curl,
LangChain, the reference's own ``open-ai-configuration`` resource pointed
at this URL) can talk straight to the TPU engine:

- ``POST /v1/chat/completions`` — messages in, completion out; set
  ``"stream": true`` for SSE ``data:`` chunks (OpenAI chunk format,
  terminated by ``data: [DONE]``).
- ``POST /v1/completions``       — prompt in (legacy text completions).
- ``POST /v1/embeddings``        — input string/list in, vectors out.
- ``GET  /v1/models``            — the single configured model.

Start it with ``langstream-tpu serve --model llama-3-8b ...`` (see
``cli.main``) or mount :func:`build_app` into an existing aiohttp site.
Options map 1:1 onto the ServiceProvider SPI: temperature, top_p, top_k,
max_tokens, stop, presence_penalty, frequency_penalty, logprobs, and a
``session_id``/``user`` field for KV-cache session affinity.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, Dict, Optional

from aiohttp import web

from langstream_tpu.api import errors as api_errors
from langstream_tpu.api.service import ChatMessage


def _sse(payload: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(payload, ensure_ascii=False).encode() + b"\n\n"


def _error(status: int, message: str) -> web.Response:
    """OpenAI-style JSON error envelope."""
    return web.json_response(
        {"error": {
            "message": message,
            "type": "invalid_request_error" if status == 400
            else "server_error",
        }},
        status=status,
    )


def _unavailable(message: str, retry_after_s: float) -> web.Response:
    """Degraded mode (engine rebuilding / queue shed): a BOUNDED 503
    with a Retry-After hint — the client-visible contract that a crash
    heals instead of 500ing (load balancers and SDKs both honor it)."""
    import math

    return web.json_response(
        {"error": {"message": message, "type": "overloaded_error"}},
        status=503,
        headers={"Retry-After": str(max(1, math.ceil(retry_after_s)))},
    )


def _options_from_request(
    body: Dict[str, Any], model: str, topk_limit: int = 0
) -> Dict[str, Any]:
    """OpenAI request params → ServiceProvider option names. Raises
    ValueError (→ HTTP 400) on malformed values so bad requests fail
    BEFORE burning a generation."""
    options: Dict[str, Any] = {"model": body.get("model") or model}
    if body.get("top_logprobs") is not None:
        try:
            n_top = int(body["top_logprobs"])
        except (TypeError, ValueError):
            raise ValueError("top_logprobs must be an integer") from None
        if not 0 <= n_top <= 20:
            raise ValueError("top_logprobs must be between 0 and 20")
        if n_top > 0 and not body.get("logprobs"):
            # OpenAI 400s this combination; silently generating and
            # returning no logprobs block would waste the whole request
            raise ValueError("top_logprobs requires logprobs: true")
        if n_top > topk_limit:
            raise ValueError(
                f"top_logprobs={n_top} exceeds this server's limit of "
                f"{topk_limit} (start the server with --logprobs-top-k "
                f">= {n_top})"
            )
        body = dict(body, top_logprobs=n_top)
    mapping = {
        "temperature": "temperature",
        "top_p": "top-p",
        "top_k": "top-k",
        "max_tokens": "max-tokens",
        "max_completion_tokens": "max-tokens",
        "stop": "stop",
        "presence_penalty": "presence-penalty",
        "frequency_penalty": "frequency-penalty",
        "logprobs": "logprobs",
        "top_logprobs": "top-logprobs",
        "seed": "seed",
    }
    if body.get("logit_bias") is not None:
        if not isinstance(body["logit_bias"], dict):
            raise ValueError("logit_bias must be an object of id -> bias")
        # OpenAI spells token ids as string keys
        options["logit-bias"] = {
            int(k): float(v) for k, v in body["logit_bias"].items()
        }
    for source, target in mapping.items():
        if body.get(source) is not None:
            options[target] = body[source]
    # session affinity for KV-cache reuse: explicit session_id, else the
    # OpenAI `user` field (stable per end user)
    session = body.get("session_id") or body.get("user")
    if session:
        options["session-id"] = str(session)
    return options


class OpenAIApiServer:
    """aiohttp wrapper serving the OpenAI surface for one model."""

    def __init__(
        self,
        completions=None,
        embeddings=None,
        *,
        model: str = "jax-local",
        host: str = "0.0.0.0",
        port: int = 8000,
        gauges=None,       # () -> Dict[str, float], like AgentHttpServer
        histograms=None,   # () -> Dict[str, Dict[str, float]]
    ) -> None:
        self.completions = completions
        self.embeddings = embeddings
        # the service's static top-K ceiling (0 = feature off): requests
        # asking for more are rejected with 400 up front instead of
        # silently truncated after a full generation. The limit lives on
        # the CompletionsService interface (top_logprobs_limit) so any
        # implementation can advertise it — not a provider-private attr.
        self._topk_limit = int(
            getattr(completions, "top_logprobs_limit", 0) or 0
        )
        self.model = model
        self.host = host
        self.port = port
        self._gauges = gauges
        self._histograms = histograms
        self._runner: Optional[web.AppRunner] = None
        self.addresses: list = []

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._text)
        app.router.add_post("/v1/embeddings", self._embeddings)
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/profile", self._profile)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.addresses = list(self._runner.addresses)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # ------------------------------------------------------------------ #
    async def _healthz(self, request) -> web.Response:
        return web.json_response({"status": "ok", "model": self.model})

    async def _metrics(self, request) -> web.Response:
        """Prometheus text from the injected gauge/histogram providers
        (the ONE exposition renderer every runner pod and the gateway
        serve through); backends wire their own — `serve` injects the
        jax-local engine snapshots."""
        from langstream_tpu.api.metrics import prometheus_text

        return web.Response(
            text=prometheus_text(
                {},
                self._gauges() if self._gauges else {},
                self._histograms() if self._histograms else {},
            ),
            content_type="text/plain",
        )

    async def _profile(self, request) -> web.Response:
        """On-demand profiler capture (``?seconds=N``): runs
        ``jax.profiler.trace`` + a device-memory snapshot into
        ``bench_artifacts/profiles/<ts>/`` while serving continues.
        One capture at a time — a concurrent request gets 409."""
        from langstream_tpu.runtime import profiling

        try:
            seconds = float(request.query.get("seconds", 3))
        except (TypeError, ValueError):
            return _error(400, "seconds must be a number")
        try:
            # capture() validates the range itself (one source of truth)
            path = await asyncio.to_thread(profiling.capture, seconds)
        except ValueError as error:
            return _error(400, str(error))
        except profiling.ProfileBusyError as error:
            return web.json_response(
                {"error": {"message": str(error), "type": "conflict"}},
                status=409,
            )
        return web.json_response({"path": path, "seconds": seconds})

    async def _models(self, request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{
                "id": self.model,
                "object": "model",
                "created": int(time.time()),
                "owned_by": "langstream-tpu",
            }],
        })

    async def _chat(self, request) -> web.StreamResponse:
        return await self._complete(request, chat=True)

    async def _text(self, request) -> web.StreamResponse:
        return await self._complete(request, chat=False)

    async def _complete(self, request, *, chat: bool) -> web.StreamResponse:
        if self.completions is None:
            return _error(503, "no completions service configured")
        # degraded-mode gate: while the engine supervisor rebuilds a
        # crashed engine, NEW work (streaming included — checked before
        # the SSE response is prepared) answers 503 + Retry-After;
        # in-flight streams are resurrected, not failed
        probe = getattr(self.completions, "available", None)
        retry_in = probe() if callable(probe) else None
        if retry_in is not None:
            return _unavailable(
                "engine is rebuilding after a crash; retry shortly",
                retry_in,
            )
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        if chat:
            raw = body.get("messages")
            if not isinstance(raw, list) or not raw:
                return _error(400, "messages must be a non-empty list")
            messages = [
                ChatMessage(
                    role=str(m.get("role", "user")),
                    content=str(m.get("content", "")),
                )
                for m in raw
            ]
            prompt_texts = None
        else:
            prompt = body.get("prompt")
            if prompt is None:
                return _error(400, "prompt is required")
            if isinstance(prompt, list):
                prompt = "".join(str(p) for p in prompt)
            # legacy completions continue the prompt verbatim (the
            # service's get_text_completions path — no chat template)
            prompt_texts = [str(prompt)]
            messages = []
            # legacy completions spell "top-K logprobs" as an INTEGER
            # `logprobs: K` (the chat API splits it into logprobs: true
            # + top_logprobs: K) — normalize so the K actually reaches
            # the top-logprobs option instead of silently meaning only
            # "include the sampled token's logprob"
            lp = body.get("logprobs")
            if (
                isinstance(lp, int) and not isinstance(lp, bool)
                and lp > 0 and body.get("top_logprobs") is None
                # feature off (limit 0): keep the pre-existing behavior
                # — sampled-token logprobs only — instead of 400ing
                # every legacy client that sends an integer
                and self._topk_limit > 0
            ):
                # clamp to the server's static ceiling: a legacy client
                # asking for more alternatives than the engine keeps
                # should get the best available, not a 400 on a request
                # shape that succeeded before the feature existed
                body = dict(body, top_logprobs=min(lp, self._topk_limit))
        try:
            options = _options_from_request(
                body, self.model, topk_limit=self._topk_limit
            )
        except (ValueError, TypeError) as error:
            return _error(400, f"invalid request parameter: {error}")
        # trace context: honor a client-supplied id, mint one otherwise —
        # the engine tags its per-request spans (TTFT/TPOT) with it and
        # the id is echoed back so clients can correlate
        trace_id = (
            request.headers.get("x-langstream-trace-id")
            or body.get("trace_id")
            or uuid.uuid4().hex
        )
        options["trace-id"] = str(trace_id)

        async def complete(consumer=None, options_override=None):
            request_options = options_override or options
            if chat:
                return await self.completions.get_chat_completions(
                    messages, request_options, consumer
                )
            return await self.completions.get_text_completions(
                prompt_texts, request_options, consumer
            )
        created = int(time.time())
        completion_id = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex
        object_name = "chat.completion" if chat else "text_completion"

        n = body.get("n", 1) if body.get("n") is not None else 1
        if isinstance(n, bool) or not isinstance(n, int):
            return _error(400, "n must be an integer")
        if not 1 <= n <= 16:
            return _error(400, "n must be between 1 and 16")
        if not body.get("stream"):
            # n > 1: independent generations fan out over the engine's
            # continuous-batching slots concurrently; explicit seeds
            # derive per-choice (seed + index) so choices differ
            # NOTE: the n choices are fully independent generations — the
            # shared prompt is prefilled n times (the engine's KV reuse is
            # per-session, not cross-slot prompt caching). Fine for small
            # n; budget TTFT accordingly for big prompts.
            try:
                per_choice = [dict(options) for _ in range(n)]
                for index, choice_options in enumerate(per_choice):
                    if n > 1 and options.get("seed") is not None:
                        choice_options["seed"] = int(options["seed"]) + index
                    if index > 0:
                        # only choice 0 keeps session affinity: n pinned
                        # slots for one session would waste warm-cache
                        # capacity and evict other sessions
                        choice_options.pop("session-id", None)
                tasks = [
                    asyncio.ensure_future(
                        complete(options_override=per_choice[i])
                    )
                    for i in range(n)
                ]
                try:
                    results = await asyncio.gather(*tasks)
                except BaseException as first:
                    # a REAL first failure cancels siblings so their
                    # engine generations free their slots instead of
                    # decoding answers nobody will read. But when the
                    # exception gather surfaces FIRST is a
                    # CancelledError (a choice's cancel racing its own
                    # completion), the real failure may still be
                    # PENDING in a sibling — cancelling it here would
                    # destroy the very error the caller needs, and the
                    # client would see a bare dropped connection
                    if not isinstance(first, asyncio.CancelledError):
                        for task in tasks:
                            if not task.done():
                                task.cancel()
                    try:
                        outcomes = await asyncio.gather(
                            *tasks, return_exceptions=True
                        )
                    except asyncio.CancelledError:
                        # the HANDLER itself was cancelled (client
                        # disconnected): free the slots and propagate
                        for task in tasks:
                            if not task.done():
                                task.cancel()
                        raise
                    # propagate the first REAL error over a
                    # cancellation artifact (explicitly, not via bare
                    # `raise`: re-raising after an await can swallow
                    # the original type)
                    if isinstance(first, asyncio.CancelledError):
                        for outcome in outcomes:
                            if isinstance(
                                outcome, BaseException
                            ) and not isinstance(
                                outcome, asyncio.CancelledError
                            ):
                                first = outcome
                                break
                    raise first
            except api_errors.UnavailableError as error:
                # typed retryable failures (queue shed, engine rebuild):
                # bounded 503s with Retry-After, never 500s
                return _unavailable(str(error), error.retry_after_s)
            except (ValueError, TypeError) as error:
                return _error(400, str(error))
            choices = []
            for index, result in enumerate(results):
                choice: Dict[str, Any] = {
                    "index": index,
                    "finish_reason": result.finish_reason,
                }
                if chat:
                    choice["message"] = {
                        "role": result.role, "content": result.content,
                    }
                else:
                    choice["text"] = result.content
                if result.logprobs is not None:
                    logprobs_block: Dict[str, Any] = {
                        "tokens": result.tokens,
                        "token_logprobs": result.logprobs,
                    }
                    n_top = int(options.get("top-logprobs") or 0)
                    if result.top_logprobs is not None and n_top > 0:
                        if chat:
                            # chat.completion format: content entries
                            # with ranked alternatives per position
                            logprobs_block["content"] = [
                                {
                                    "token": tok,
                                    "logprob": lp,
                                    "top_logprobs": [
                                        {"token": t2, "logprob": lp2}
                                        for t2, lp2 in tops[:n_top]
                                    ],
                                }
                                for tok, lp, tops in zip(
                                    result.tokens, result.logprobs,
                                    result.top_logprobs,
                                )
                            ]
                        else:
                            # legacy text_completion format: a
                            # {token: logprob} dict per position,
                            # parallel to `tokens`. Distinct token ids
                            # can decode to the same text; keep the
                            # FIRST (highest-ranked) logprob instead of
                            # letting later duplicates overwrite it —
                            # the dict may then hold fewer than n_top
                            # keys, which is inherent to the legacy
                            # text-keyed format
                            legacy = []
                            for tops in result.top_logprobs:
                                row: dict = {}
                                for t2, lp2 in tops[:n_top]:
                                    row.setdefault(t2, lp2)
                                legacy.append(row)
                            logprobs_block["top_logprobs"] = legacy
                    choice["logprobs"] = logprobs_block
                choices.append(choice)
            completion_tokens = sum(r.completion_tokens for r in results)
            return web.json_response({
                "id": completion_id,
                "object": object_name,
                "created": created,
                "model": options["model"],
                "choices": choices,
                "usage": {
                    "prompt_tokens": results[0].prompt_tokens,
                    "completion_tokens": completion_tokens,
                    "total_tokens": (
                        results[0].prompt_tokens + completion_tokens
                    ),
                },
            }, headers={"x-langstream-trace-id": str(trace_id)})
        if n > 1:
            return _error(400, "streaming supports n=1 only")

        # streaming: SSE chunks in the OpenAI chunk format
        response = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "x-langstream-trace-id": str(trace_id),
        })
        await response.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()

        class Consumer:
            def consume_chunk(self, answer_id, index, chunk, last):
                queue.put_nowait((chunk.content, last))

        async def pump():
            try:
                return await complete(Consumer())
            except BaseException:
                # wake the SSE loop: without a terminal item it would
                # await queue.get() forever on a failed generation
                queue.put_nowait(("", True))
                raise

        task = asyncio.ensure_future(pump())
        chunk_object = "chat.completion.chunk" if chat else "text_completion"
        try:
            if chat:
                await response.write(_sse({
                    "id": completion_id, "object": chunk_object,
                    "created": created, "model": options["model"],
                    "choices": [{
                        "index": 0,
                        "delta": {"role": "assistant", "content": ""},
                        "finish_reason": None,
                    }],
                }))
            while True:
                content, last = await queue.get()
                delta_choice: Dict[str, Any] = {
                    "index": 0,
                    "finish_reason": None,
                }
                if chat:
                    delta_choice["delta"] = {"content": content}
                else:
                    delta_choice["text"] = content
                if content:
                    await response.write(_sse({
                        "id": completion_id, "object": chunk_object,
                        "created": created, "model": options["model"],
                        "choices": [delta_choice],
                    }))
                if last:
                    break
            try:
                result = await task
            except Exception as error:  # noqa: BLE001
                await response.write(_sse({
                    "error": {"message": str(error), "type": "server_error"},
                }))
                await response.write(b"data: [DONE]\n\n")
                await response.write_eof()
                return response
            final_choice: Dict[str, Any] = {
                "index": 0,
                "finish_reason": result.finish_reason,
            }
            if chat:
                final_choice["delta"] = {}
            else:
                final_choice["text"] = ""
            await response.write(_sse({
                "id": completion_id, "object": chunk_object,
                "created": created, "model": options["model"],
                "choices": [final_choice],
                "usage": {
                    "prompt_tokens": result.prompt_tokens,
                    "completion_tokens": result.completion_tokens,
                    "total_tokens": (
                        result.prompt_tokens + result.completion_tokens
                    ),
                },
            }))
            await response.write(b"data: [DONE]\n\n")
        finally:
            if not task.done():
                # client went away mid-stream: cancel the generation so
                # the engine frees the slot instead of finishing unread
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        await response.write_eof()
        return response

    async def _embeddings(self, request) -> web.Response:
        if self.embeddings is None:
            return _error(503, "no embeddings service configured")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        texts = body.get("input")
        if texts is None:
            return _error(400, "input is required")
        if isinstance(texts, str):
            texts = [texts]
        vectors = await self.embeddings.compute_embeddings(
            [str(t) for t in texts]
        )
        return web.json_response({
            "object": "list",
            "model": body.get("model") or self.model,
            "data": [
                {"object": "embedding", "index": i, "embedding": vector}
                for i, vector in enumerate(vectors)
            ],
            "usage": {"prompt_tokens": 0, "total_tokens": 0},
        })
