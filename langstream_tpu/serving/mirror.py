"""Multi-host SPMD serving: the dispatch mirror.

A model sharded across hosts (tp spanning a multi-host TPU slice) needs
EVERY process of the replica to enter the same jit programs in the same
order — XLA collectives ride inside those programs. Only host 0 sees
request traffic (gateway/runner/HTTP run there), and its engine makes
timing-dependent host decisions (admission grouping, bucket choice,
chunk size). Followers therefore cannot recompute the schedule; they
must REPLAY it.

The contract (reference has no analogue — it never spans a model across
processes; this is the TPU-native design for BASELINE #5-style serving
at >8-chip scale):

- host 0 runs the normal :class:`DecodeEngine` with ``engine.mirror``
  set to a :class:`DispatchMirror`. Every device dispatch publishes a
  compact record (kind, static meta, host numpy args) BEFORE the local
  dispatch; records form one FIFO stream.
- each follower host builds the identical engine (same config, same
  seed/params/mesh — weights load deterministically) and replays the
  stream with :class:`FollowerExecutor`: same jits, same static shapes,
  same host args, its own shard of cache/params/counts.
- pipelined decode chains from ON-DEVICE carries on host 0; the
  ``decode_chained`` record carries no arrays — the follower chains
  from its OWN previous decode outputs, which hold identical values by
  SPMD determinism.
- ``kv_layout: paged`` replays too: paged dispatch records carry each
  row's block-table slice (small int32 host metadata — pool data never
  crosses the wire), and copy-on-write block copies publish their own
  ``block_copy`` records, so the follower applies the identical pool
  mutations to its kv-head shard without running the block
  allocator/prefix-cache/LRU bookkeeping itself — those are host-0
  decisions already baked into the tables it receives.
- ``prefill_mode: mixed`` replays as ``mixed`` records: one per fused
  prefill+decode step, carrying the per-row token counts (offsets /
  num_tokens / write/decode/completes masks) plus the tables and
  sampling arrays — the follower enters the same ``_get_mixed(width)``
  jit with identical args, so the chunked-prefill schedule host 0
  chose is baked into the stream like every other timing decision.
- the mixed-step carry chains too: a ``mixed_chained`` record carries
  ONLY the window-delta metadata (token windows + per-row counts +
  masks) — the follower reuses tables/sampling arrays and the previous
  step's sampled tokens from its own mixed carry, which hold identical
  values by SPMD determinism (the ``decode_chained`` contract, plus the
  small host-predictable delta the mixed step inherently needs).

Transport is a length-prefixed JSON-header + raw-array-bytes frame
stream over TCP (deliberately NOT pickle — nothing executable crosses
the wire): host 0 listens,
followers connect before serving starts (`expected` blocks until all
joined, because a follower joining mid-stream would miss cache state).
jax.distributed.initialize (runtime/multihost.py) must already be up so
the global mesh exists on every process.
"""

from __future__ import annotations

import hashlib
import json
import logging
import queue
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def config_fingerprint(config: Dict[str, Any]) -> bytes:
    """16-byte digest of the serving config. Leader and followers must
    run the SAME model/engine configuration — mismatched shapes would
    not fail loudly (each process jit-compiles its own variants) but
    would silently diverge. The handshake compares digests."""
    canonical = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(canonical.encode()).digest()[:16]


_MAGIC = b"LSM1"
_FINGERPRINT_LEN = 16
_ANY_FINGERPRINT = bytes(_FINGERPRINT_LEN)  # all-zero = skip the check
_HEADER = struct.Struct("!I")  # payload length
# record payloads are NOT pickle: followers deserialize data from the
# network, so the wire format is a JSON header (kind, meta, array
# dtypes/shapes) plus raw array bytes — nothing executable
_ALLOWED_DTYPES = frozenset(
    ("int32", "uint32", "float32", "bool", "int64", "float64")
)


def _encode_record(kind: str, meta: Dict[str, Any], arrays: list) -> bytes:
    specs = []
    buffers: List[bytes] = []
    for array in arrays:
        # np.asarray, NOT ascontiguousarray: the latter promotes 0-d
        # scalars to shape (1,), and the copy-record jit needs true
        # scalars for lax.dynamic_slice indices
        array = np.asarray(array)
        specs.append({"dtype": array.dtype.name, "shape": list(array.shape)})
        buffers.append(array.tobytes())  # tobytes is C-order regardless
    header = json.dumps(
        {"kind": kind, "meta": meta, "arrays": specs}
    ).encode()
    return b"".join(
        [_HEADER.pack(len(header)), header, *buffers]
    )


def _send_record(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks: List[bytes] = []
    while n:
        part = sock.recv(n)
        if not part:
            raise ConnectionError("mirror stream closed")
        chunks.append(part)
        n -= len(part)
    return b"".join(chunks)


def _recv_record(sock: socket.socket) -> Tuple[str, Dict[str, Any], list]:
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    payload = _recv_exact(sock, length)
    (header_len,) = _HEADER.unpack(payload[: _HEADER.size])
    cursor = _HEADER.size + header_len
    header = json.loads(payload[_HEADER.size: cursor])
    arrays = []
    for spec in header["arrays"]:
        dtype = spec["dtype"]
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError(f"mirror: disallowed dtype {dtype!r}")
        shape = tuple(int(d) for d in spec["shape"])
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        size = count * np.dtype(dtype).itemsize
        arrays.append(
            np.frombuffer(
                payload[cursor: cursor + size], dtype=dtype
            ).reshape(shape)
        )
        cursor += size
    if cursor != len(payload):
        raise ValueError("mirror: record length mismatch")
    return header["kind"], header["meta"], arrays


class DispatchMirror:
    """Host-0 side: accept follower connections, then fan every
    published dispatch record out to all of them in order.

    ``publish`` only enqueues (the engine thread never blocks on the
    network); a single writer thread preserves FIFO order. A follower
    that drops its connection mid-serve is fatal for the replica — the
    next collective would deadlock anyway — so the error is raised into
    the engine thread via the queue. The queue is bounded: a follower
    that falls persistently behind the leader's dispatch rate (records
    are small, so the bound is generous) is the same fatal condition as
    a dropped follower — without it the leader accumulates encoded
    records without limit and the engine gets no backpressure signal
    until memory pressure."""

    # dispatch records are ~100 bytes + small host arrays; 65536 queued
    # records is minutes of serving headroom, yet bounds leader memory
    QUEUE_MAXSIZE = 65536
    # how long publish() may block on a full queue before declaring the
    # follower link dead
    PUBLISH_TIMEOUT_S = 60.0

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        fingerprint: bytes = _ANY_FINGERPRINT,
    ) -> None:
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self._fingerprint = fingerprint
        self._followers: List[socket.socket] = []
        self._queue: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=self.QUEUE_MAXSIZE
        )
        self._writer: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._closed = False

    def wait_for_followers(self, expected: int, timeout: float = 300.0) -> None:
        """Block until ``expected`` followers complete the handshake,
        then start the writer. Must run before any traffic is served."""
        self._server.settimeout(timeout)
        while len(self._followers) < expected:
            conn, addr = self._server.accept()
            # bound the handshake read too — a connection that sends no
            # bytes (port scanner, health probe) must not hang startup
            conn.settimeout(10.0)
            try:
                magic = _recv_exact(conn, len(_MAGIC))
                theirs = _recv_exact(conn, _FINGERPRINT_LEN)
            except (socket.timeout, ConnectionError, OSError):
                conn.close()
                logger.warning("mirror: handshake timeout from %s", addr)
                continue
            if magic != _MAGIC:
                conn.close()
                logger.warning("mirror: bad handshake from %s", addr)
                continue
            if (
                self._fingerprint != _ANY_FINGERPRINT
                and theirs != _ANY_FINGERPRINT
                and theirs != self._fingerprint
            ):
                conn.close()
                logger.error(
                    "mirror: follower %s runs a DIFFERENT serving config "
                    "(fingerprint mismatch) — rejected; replay on "
                    "mismatched shapes would silently diverge", addr,
                )
                continue
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._followers.append(conn)
            logger.info(
                "mirror: follower %d/%d connected from %s",
                len(self._followers), expected, addr,
            )
        self._writer = threading.Thread(
            target=self._write_loop, name="mirror-writer", daemon=True
        )
        self._writer.start()

    def publish(self, kind: str, meta: Dict[str, Any], arrays: list) -> None:
        if self._error is not None:
            raise RuntimeError("mirror writer failed") from self._error
        try:
            self._queue.put(
                _encode_record(kind, meta, arrays),
                timeout=self.PUBLISH_TIMEOUT_S,
            )
        except queue.Full:
            # lint: allow(cross-thread-mutation) -- benign latched
            #   error: each writer performs a single None→exception
            #   transition on a word-sized slot; a reader seeing a stale
            #   None enqueues at most one extra record before failing
            self._error = RuntimeError(
                f"mirror publish queue full for {self.PUBLISH_TIMEOUT_S:.0f}s"
                " — follower cannot keep up with the dispatch rate"
            )
            raise RuntimeError("mirror writer failed") from self._error

    def _write_loop(self) -> None:
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            for follower in self._followers:
                try:
                    _send_record(follower, payload)
                except OSError as error:
                    self._error = error
                    logger.error("mirror: follower write failed: %s", error)
                    return

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass  # writer is wedged; the bounded join below handles it
        if self._writer is not None:
            self._writer.join(timeout=10)
        for follower in self._followers:
            try:
                follower.close()
            except OSError:
                pass
        self._server.close()


class FollowerExecutor:
    """Follower side: replay host 0's dispatch stream on this process's
    shard of the global mesh.

    The engine passed in must be constructed with the same config as
    host 0's and must NOT be started — the executor owns its cache and
    counts. Outputs other than cache/counts are dropped (host 0 emits
    the tokens); the previous decode outputs are retained so
    ``decode_chained`` records can chain exactly like host 0 does."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self._sock: Optional[socket.socket] = None
        # previous decode output, for chained chunks:
        # (final_tokens, final_lengths, active_arg, tables, sampling)
        # — tables is None on dense engines
        self._carry: Optional[Tuple[Any, Any, Any, Any, tuple]] = None
        # previous mixed-step output, for mixed_chained records:
        # (sampled, tables, sampling) — the device-resident operands a
        # chained mixed record deliberately does not carry
        self._mixed_carry: Optional[Tuple[Any, Any, tuple]] = None
        self.records = 0

    def connect(
        self,
        host: str,
        port: int,
        timeout: float = 300.0,
        fingerprint: bytes = _ANY_FINGERPRINT,
    ) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.sendall(_MAGIC + fingerprint)

    def run(self) -> int:
        """Replay records until a ``stop`` record or stream close.
        Returns the number of records executed."""
        assert self._sock is not None, "connect() first"
        try:
            while True:
                try:
                    kind, meta, arrays = _recv_record(self._sock)
                except ConnectionError:
                    logger.info("mirror: stream closed, follower exiting")
                    return self.records
                if kind == "stop":
                    return self.records
                # chaos (LANGSTREAM_FAULTS=mirror_follower@step=N): a
                # follower dying mid-replay — the leader-side handling
                # of a dropped follower is part of the fault surface
                from langstream_tpu.runtime import faults

                faults.check("mirror_follower")
                self._execute(kind, meta, arrays)
                self.records += 1
        finally:
            self._sock.close()

    def _execute(self, kind: str, meta: Dict[str, Any], arrays: list) -> None:
        engine = self.engine
        # paged dispatches carry one extra operand — the block-table
        # rows — in dispatch-arg position (after slot_ids / active);
        # engine.paged tells the replay how to split the record back
        # into the jit's exact argument tuple
        extra = 1 if engine.paged else 0
        # leader dispatches run under the engine mesh (sharding
        # constraints/shard_map resolve against the ambient mesh);
        # replay must too or tp>1 followers diverge
        with engine.mesh:
            if kind == "prefill":
                run = engine._get_prefill(meta["bucket"])
                engine.cache, engine._counts, _, _, _ = run(
                    engine.params, engine.cache, *arrays[:3 + extra],
                    engine._counts, *arrays[3 + extra:],
                )
            elif kind == "prefill_offset":
                run = engine._get_prefill_offset(meta["bucket"])
                engine.cache, engine._counts, _, _, _ = run(
                    engine.params, engine.cache, *arrays[:4 + extra],
                    engine._counts, *arrays[4 + extra:],
                )
            elif kind == "copy":
                run = engine._get_copy_prefix(meta["bucket"])
                (engine.cache,) = run(engine.params, engine.cache, *arrays)
            elif kind == "block_copy":
                # the paged COW primitive: duplicate pool block src->dst
                # on this process's kv-head shard
                run = engine._get_block_copy()
                (engine.cache,) = run(engine.params, engine.cache, *arrays)
            elif kind == "mixed":
                # mixed prefill+decode step (prefill_mode: mixed): the
                # record carries per-row token counts + the mask trio +
                # the full block tables + carry operands in dispatch-arg
                # position; the sampled tokens become this process's
                # mixed carry (identical to host 0's by SPMD
                # determinism) so mixed_chained records can chain
                run = engine._get_mixed(meta["width"])
                engine.cache, engine._counts, sampled, _, _ = run(
                    engine.params, engine.cache, *arrays[:7],
                    engine._counts, *arrays[7:],
                )
                # arrays: 0-5 window/count metadata, 6 tables,
                # 7 prev_sampled, 8 chain_mask, 9.. sampling arrays
                self._mixed_carry = (
                    sampled, arrays[6], tuple(arrays[9:])
                )
            elif kind == "mixed_chained":
                assert self._mixed_carry is not None, \
                    "chained mixed step before any mixed step"
                prev_sampled, tables, sampling = self._mixed_carry
                run = engine._get_mixed(meta["width"])
                engine.cache, engine._counts, sampled, _, _ = run(
                    engine.params, engine.cache, *arrays[:6], tables,
                    engine._counts, prev_sampled, arrays[6], *sampling,
                )
                self._mixed_carry = (sampled, tables, sampling)
            elif kind == "decode":
                tokens, lengths, active = arrays[:3]
                tables = arrays[3] if extra else None
                self._decode(
                    meta["steps"], tokens, lengths, active, tables,
                    tuple(arrays[3 + extra:]),
                )
            elif kind == "decode_chained":
                assert self._carry is not None, \
                    "chained decode before any decode"
                tokens, lengths, active, tables, sampling = self._carry
                self._decode(
                    meta["steps"], tokens, lengths, active, tables, sampling
                )
            else:
                raise ValueError(f"unknown mirror record kind {kind!r}")

    def _decode(self, steps, tokens, lengths, active, tables, sampling) -> None:
        engine = self.engine
        run = engine._get_decode(steps)
        paged_args = (tables,) if tables is not None else ()
        (
            engine.cache, engine._counts, _, _, _,
            final_tokens, final_lengths,
        ) = run(
            engine.params, engine.cache, tokens, lengths, active, active,
            *paged_args, engine._counts, *sampling,
        )
        self._carry = (final_tokens, final_lengths, active, tables, sampling)
