"""The gateway server: WS produce/consume/chat + HTTP produce/service.

Endpoint and wire parity with the reference gateway:

- WS ``/v1/{produce|consume|chat}/{tenant}/{application}/{gateway}``
  (``websocket/WebSocketConfig.java:46-48``); query args use the
  reference's conventions (``GatewayRequestHandler.java:105-116``):
  ``param:<name>=...`` for declared gateway parameters,
  ``option:<name>=...`` for options (e.g. ``option:position=earliest``),
  ``credentials=...`` / ``test-credentials=...`` for auth.
- Produce frames are ``{"key", "value", "headers"}``
  (``api/ProduceRequest.java:20``); consume pushes are
  ``{"record": {...}, "offset": "..."}`` (``api/ConsumePushMessage.java:20``).
- HTTP ``POST /api/gateways/produce/{tenant}/{app}/{gateway}`` and the
  ``service`` gateway ``/api/gateways/service/...`` topic round-trip
  correlated by ``langstream-service-request-id``
  (``http/GatewayResource.java:74-96,156-190``).
- Gateway lifecycle events (ClientConnected/Disconnected) go to the
  configured events-topic (``events/EventRecord.java:13-29``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from aiohttp import WSMsgType, web

from langstream_tpu.api.metrics import MetricsReporter, prometheus_text
from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.api.topics import OffsetPosition
from langstream_tpu.gateway.auth import (
    AuthenticationFailed,
    Principal,
    create_auth_provider,
)
from langstream_tpu.model.application import Application, Gateway
from langstream_tpu.runtime.tracing import (
    TRACE_ID_HEADER,
    get_tracer,
    new_trace_id,
)

logger = logging.getLogger(__name__)


class GatewayError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status


class _RegisteredApp:
    def __init__(self, application: Application, topic_runtime) -> None:
        self.application = application
        self.topic_runtime = topic_runtime
        self.producers: Dict[str, Any] = {}

    async def producer(self, topic: str):
        producer = self.producers.get(topic)
        if producer is None:
            producer = self.topic_runtime.create_producer("gateway", {"topic": topic})
            await producer.start()
            self.producers[topic] = producer
        return producer


class GatewayServer:
    """Serves every registered application's gateways on one port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8091) -> None:
        self.host = host
        self.port = port
        self._apps: Dict[Tuple[str, str], _RegisteredApp] = {}
        self._runner: Optional[web.AppRunner] = None
        self._auth_cache: Dict[int, Any] = {}
        # observability: request-entry spans (NOOP unless tracing is on)
        # + counters served at /metrics through the shared exposition
        # renderer — same format as runner pods and the OpenAI server
        self.tracer = get_tracer("gateway")
        self.metrics = MetricsReporter(prefix="gateway")
        # fleet layer (langstream_tpu/fleet): when a router/controller
        # is registered, produce paths stamp a replica-affinity header
        # and /metrics serves the fleet gauges
        self._fleet = None

    # ------------------------------------------------------------------ #
    # registration / lifecycle
    # ------------------------------------------------------------------ #
    def register(self, tenant: str, application: Application, topic_runtime) -> None:
        self._apps[(tenant, application.application_id)] = _RegisteredApp(
            application, topic_runtime
        )

    def register_local_runner(self, local_runner, tenant: str = "default") -> None:
        self.register(tenant, local_runner.application, local_runner.topic_runtime)

    def register_fleet(self, controller) -> None:
        """Attach a fleet router/controller (``fleet.FleetRouter`` or
        ``fleet.FleetController``): produce paths consult it for a
        prefix-affinity replica and /metrics merges its gauges. The
        gateway stays fully functional without one — routing is an
        overlay, not a dependency."""
        self._fleet = controller

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/v1/produce/{tenant}/{application}/{gateway}", self._ws_produce)
        app.router.add_get("/v1/consume/{tenant}/{application}/{gateway}", self._ws_consume)
        app.router.add_get("/v1/chat/{tenant}/{application}/{gateway}", self._ws_chat)
        app.router.add_post(
            "/api/gateways/produce/{tenant}/{application}/{gateway}", self._http_produce
        )
        app.router.add_post(
            "/api/gateways/service/{tenant}/{application}/{gateway}", self._http_service
        )
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        # local UI (reference: `langstream apps ui`)
        app.router.add_get("/ui/{tenant}/{application}", self._ui_page)
        app.router.add_get("/ui/api/{tenant}/{application}", self._ui_api)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        logger.info("gateway listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _healthz(self, request) -> web.Response:
        return web.json_response({"status": "OK", "apps": len(self._apps)})

    async def _metrics(self, request) -> web.Response:
        gauges = {"gateway_registered_apps": float(len(self._apps))}
        histograms = dict(self.metrics.histogram_snapshots())
        # `apps run` hosts the gateway in the SAME process as the TPU
        # engine: surface the engine's efficiency gauges (MFU/MBU,
        # goodput, SLO burn rates, watchdog trips) here too, so every
        # scrape surface of the process tells the same story. Lazy via
        # sys.modules — a gateway-only process never imports the engine.
        import sys as _sys

        engine_module = _sys.modules.get(
            "langstream_tpu.providers.jax_local.engine"
        )
        if engine_module is not None:
            gauges.update(engine_module.engines_snapshot())
            histograms.update(engine_module.engines_histograms())
        else:
            # gateway-only process: the engine families are absent, but
            # the journey ledger's route stage is sampled HERE — its
            # per-stage histograms must still reach this surface
            from langstream_tpu.runtime.journey import stage_histograms

            histograms.update(stage_histograms())
        # fleet routing/autoscaling gauges (per-replica queue depth and
        # state, affinity hit rate, replica counts) — the `top` fleet
        # panel reads exactly these families
        if self._fleet is not None:
            gauges.update(self._fleet.gauges())
        return web.Response(
            text=prometheus_text(
                self.metrics.snapshot(),
                gauges,
                histograms,
            ),
            content_type="text/plain",
        )

    def _ui_app(self, request):
        key = (request.match_info["tenant"], request.match_info["application"])
        registered = self._apps.get(key)
        if registered is None:
            raise web.HTTPNotFound(text=f"no application {key}")
        return registered.application

    async def _ui_page(self, request) -> web.Response:
        from langstream_tpu.gateway.ui import render_page

        self._ui_app(request)  # 404 for unknown apps
        return web.Response(
            text=render_page(
                request.match_info["tenant"],
                request.match_info["application"],
            ),
            content_type="text/html",
        )

    async def _ui_api(self, request) -> web.Response:
        from langstream_tpu.gateway.ui import describe

        return web.json_response(describe(self._ui_app(request)))

    # ------------------------------------------------------------------ #
    # request validation (GatewayRequestHandler.validateRequest parity)
    # ------------------------------------------------------------------ #
    def _resolve(
        self, request, expected_type: str
    ) -> Tuple[_RegisteredApp, Gateway, Dict[str, str], Dict[str, str], Optional[str]]:
        tenant = request.match_info["tenant"]
        application_id = request.match_info["application"]
        gateway_id = request.match_info["gateway"]
        registered = self._apps.get((tenant, application_id))
        if registered is None:
            raise GatewayError(404, f"unknown application {tenant}/{application_id}")
        gateway = None
        for candidate in registered.application.gateways:
            if candidate.id == gateway_id:
                gateway = candidate
                break
        if gateway is None:
            raise GatewayError(404, f"unknown gateway {gateway_id!r}")
        if gateway.type != expected_type:
            raise GatewayError(
                400,
                f"gateway {gateway_id!r} is of type {gateway.type!r}, "
                f"expected {expected_type!r}",
            )
        options: Dict[str, str] = {}
        parameters: Dict[str, str] = {}
        credentials: Optional[str] = None
        for key, value in request.query.items():
            if key in ("credentials", "test-credentials"):
                credentials = value
            elif key.startswith("option:"):
                options[key[len("option:"):]] = value
            elif key.startswith("param:"):
                parameters[key[len("param:"):]] = value
            else:
                raise GatewayError(
                    400,
                    f"invalid query parameter {key!r}. To specify a gateway "
                    "parameter, use the format param:<parameter_name>. "
                    "To specify an option, use the format option:<option_name>.",
                )
        required = set(gateway.parameters) | self._referenced_parameters(gateway)
        for name in sorted(required):
            if not parameters.get(name):
                raise GatewayError(
                    400,
                    f"missing required parameter {name!r}. "
                    f"Required parameters: {sorted(required)}",
                )
        unknown = set(parameters) - required
        if unknown:
            raise GatewayError(400, f"unknown parameters: {sorted(unknown)}")
        return registered, gateway, parameters, options, credentials

    @staticmethod
    def _referenced_parameters(gateway: Gateway) -> set:
        names = set()
        for options in (
            gateway.produce_options,
            gateway.consume_options.get("filters", {}),
            gateway.chat_options,
        ):
            for header in options.get("headers", []) or []:
                name = header.get("value-from-parameters")
                if name:
                    names.add(name)
        return names

    async def _authenticate(
        self, gateway: Gateway, credentials: Optional[str]
    ) -> Optional[Principal]:
        if not gateway.authentication:
            return Principal(credentials or "anonymous") if credentials else None
        provider_key = id(gateway)
        provider = self._auth_cache.get(provider_key)
        if provider is None:
            provider = create_auth_provider(gateway.authentication)
            self._auth_cache[provider_key] = provider
        if credentials is None:
            raise GatewayError(401, "credentials required")
        try:
            return await provider.authenticate(credentials)
        except AuthenticationFailed as error:
            raise GatewayError(401, str(error)) from error

    @staticmethod
    def _resolve_headers(
        entries: List[Dict[str, Any]],
        parameters: Dict[str, str],
        principal: Optional[Principal],
    ) -> List[Tuple[str, str]]:
        """Resolve configured gateway headers: literal ``value``,
        ``value-from-parameters`` or ``value-from-authentication``. Entries
        without a ``key`` default to the client-session header (the shape
        used by chat-options in the reference examples)."""
        out = []
        for entry in entries or []:
            key = entry.get("key", "langstream-client-session-id")
            if "value" in entry:
                value = entry["value"]
            elif "value-from-parameters" in entry:
                value = parameters.get(entry["value-from-parameters"], "")
            elif "value-from-authentication" in entry:
                if principal is None:
                    raise GatewayError(401, "authentication required for header")
                value = principal.get(entry["value-from-authentication"])
            else:
                value = ""
            out.append((key, str(value) if value is not None else ""))
        return out

    async def _emit_event(
        self, registered: _RegisteredApp, gateway: Gateway, event_type: str,
        parameters: Dict[str, str],
    ) -> None:
        topic = gateway.events_topic
        if not topic:
            return
        producer = await registered.producer(topic)
        await producer.write(
            Record(
                value={
                    "type": event_type,
                    "timestamp": now_millis(),
                    "source": {"gateway": gateway.id, "type": gateway.type},
                    "data": {"user-parameters": parameters},
                }
            )
        )

    # ------------------------------------------------------------------ #
    # produce
    # ------------------------------------------------------------------ #
    @staticmethod
    def _parse_produce(payload: str) -> Tuple[Any, Any, List[Tuple[str, str]]]:
        try:
            body = json.loads(payload)
        except json.JSONDecodeError as error:
            raise GatewayError(400, f"invalid JSON: {error}") from error
        if not isinstance(body, dict):
            raise GatewayError(400, "produce payload must be a JSON object")
        headers = [
            (str(k), str(v)) for k, v in (body.get("headers") or {}).items()
        ]
        return body.get("key"), body.get("value"), headers

    @staticmethod
    def _stamp_trace(
        headers: Tuple[Tuple[str, str], ...]
    ) -> Tuple[Tuple[Tuple[str, str], ...], str]:
        """Ensure a ``langstream-trace-id`` header: keep a client-supplied
        one (cross-system traces), mint one otherwise. Every ingress path
        stamps here so one id follows the request through every topic
        hop, runner span, and engine span."""
        for key, value in headers:
            if key == TRACE_ID_HEADER and value:
                return headers, str(value)
        trace_id = new_trace_id()
        return headers + ((TRACE_ID_HEADER, trace_id),), trace_id

    def _route_decision(self, value: Any, user_headers=()):
        """The fleet router's verdict for one produce, or None (no
        fleet attached / unroutable). Split out of
        :meth:`_fleet_headers` so the journey ledger sees the decision
        itself — policy and matched-prefix class — not just the stamped
        header."""
        if self._fleet is None:
            return None
        from langstream_tpu.fleet.router import (
            REPLICA_HEADER,
            NoRoutableReplica,
        )

        tokens = None
        if isinstance(value, dict):
            raw = value.get("tokens")
            if isinstance(raw, list) and all(
                isinstance(t, int) for t in raw
            ):
                tokens = raw
        pin = next(
            (
                str(v) for k, v in user_headers
                if k == REPLICA_HEADER and v
            ),
            None,
        )
        try:
            decision = self._fleet.route(tokens, session_replica=pin)
        except NoRoutableReplica:
            self.metrics.counter("fleet_unroutable").count()
            return None
        if decision.policy == "sticky":
            self.metrics.counter("fleet_sticky").count()
        self.metrics.counter("fleet_routed").count()
        return decision

    def _fleet_headers(
        self,
        value: Any,
        user_headers: Tuple[Tuple[str, str], ...] = (),
    ) -> Tuple[Tuple[str, str], ...]:
        """Prefix-affinity routing at the front door: when a fleet
        router is registered, pick the replica whose resident chain set
        best matches the session's token prefix (``tokens`` in a dict
        payload; token-less payloads fall back least-queue-depth) and
        stamp it as the ``langstream-replica`` header, so downstream
        consumers — and keyed partitioners — can honor the decision.

        Session stickiness (ROADMAP item 4): a follow-up carrying the
        stamped ``langstream-replica`` header from a prior reply PINS
        its session's replica — the warm KV lives there NOW, before its
        chain digests have gossiped — and a stale/condemned pin falls
        back to digest scoring, re-stamping the new decision.

        Never fails the produce: an unroutable fleet degrades to the
        pre-fleet blind path."""
        decision = self._route_decision(value, user_headers)
        if decision is None:
            return ()
        from langstream_tpu.fleet.router import REPLICA_HEADER

        return ((REPLICA_HEADER, decision.replica_id),)

    def _record_route(
        self, trace_id: str, decision, start_wall: float, dur_s: float
    ) -> None:
        """The journey ledger's ``route`` stage on the gateway: a
        histogram sample for this /metrics surface, a ``gateway.route``
        trace event, and a ``journey`` flight record when the recorder
        is armed — so fleet-wide joins see who decided and why, not
        just where the request landed."""
        from langstream_tpu.runtime import flight
        from langstream_tpu.runtime.journey import STAGE_SECONDS

        STAGE_SECONDS["route"].observe(max(0.0, dur_s))
        if decision is None:
            return
        attrs = {
            "policy": decision.policy,
            "replica": decision.replica_id,
            "prefix_class": (
                "host" if getattr(decision, "matched_host_blocks", 0)
                else "warm" if getattr(decision, "matched_blocks", 0)
                else "cold"
            ),
        }
        if self.tracer.enabled:
            self.tracer.event(
                "gateway.route",
                max(0.0, dur_s),
                trace_id=trace_id,
                start_wall=start_wall,
                **attrs,
            )
        if flight.RECORDER.enabled:
            flight.record(
                "journey",
                trace_id=trace_id,
                stages=[{
                    "stage": "route",
                    "start": start_wall,
                    "end": start_wall + max(0.0, dur_s),
                    **attrs,
                }],
            )

    async def _do_produce(
        self, registered, gateway, parameters, principal, payload: str
    ) -> None:
        key, value, user_headers = self._parse_produce(payload)
        gateway_headers = self._resolve_headers(
            gateway.produce_options.get("headers"), parameters, principal
        )
        route_t0 = time.perf_counter()
        route_wall = time.time()
        decision = self._route_decision(value, tuple(user_headers))
        route_dur = time.perf_counter() - route_t0
        fleet_headers: Tuple[Tuple[str, str], ...] = ()
        if decision is not None:
            from langstream_tpu.fleet.router import REPLICA_HEADER

            fleet_headers = ((REPLICA_HEADER, decision.replica_id),)
        if self._fleet is not None:
            # the routing layer owns the replica header: drop any
            # client-supplied pin (honored pins re-stamp the same
            # value; stale pins must not ride beside the new decision
            # — and when the whole fleet is unroutable, forwarding the
            # client's echoed pin would steer the session to a replica
            # the router just refused)
            from langstream_tpu.fleet.router import REPLICA_HEADER

            user_headers = [
                h for h in user_headers if h[0] != REPLICA_HEADER
            ]
        headers, trace_id = self._stamp_trace(
            tuple(user_headers)
            + tuple(gateway_headers)
            + fleet_headers
        )
        if self._fleet is not None:
            self._record_route(trace_id, decision, route_wall, route_dur)
        with self.tracer.span(
            "gateway.produce", trace_id=trace_id,
            gateway=gateway.id, topic=gateway.topic,
        ):
            await (await registered.producer(gateway.topic)).write(
                Record(value=value, key=key, headers=headers)
            )
        self.metrics.counter("records_produced").count()

    async def _ws_produce(self, request) -> web.WebSocketResponse:
        try:
            registered, gateway, parameters, _options, credentials = self._resolve(
                request, "produce"
            )
            principal = await self._authenticate(gateway, credentials)
        except GatewayError as error:
            raise web.HTTPBadRequest(text=str(error)) if error.status == 400 else (
                web.HTTPNotFound(text=str(error)) if error.status == 404
                else web.HTTPUnauthorized(text=str(error))
            )
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(registered, gateway, "ClientConnected", parameters)
        try:
            async for message in ws:
                if message.type != WSMsgType.TEXT:
                    continue
                try:
                    await self._do_produce(
                        registered, gateway, parameters, principal, message.data
                    )
                    await ws.send_json({"status": "OK"})
                except GatewayError as error:
                    await ws.send_json({"status": "BAD_REQUEST", "reason": str(error)})
        finally:
            await self._emit_event(registered, gateway, "ClientDisconnected", parameters)
        return ws

    async def _http_produce(self, request) -> web.Response:
        try:
            registered, gateway, parameters, _options, credentials = self._resolve(
                request, "produce"
            )
            principal = await self._authenticate(gateway, credentials)
            await self._do_produce(
                registered, gateway, parameters, principal, await request.text()
            )
        except GatewayError as error:
            return web.json_response(
                {"status": "ERROR", "reason": str(error)}, status=error.status
            )
        return web.json_response({"status": "OK"})

    # ------------------------------------------------------------------ #
    # consume
    # ------------------------------------------------------------------ #
    @staticmethod
    def _record_to_json(record: Record) -> Dict[str, Any]:
        value = record.value
        if isinstance(value, bytes):
            value = value.decode("utf-8", errors="replace")
        offset = ""
        partition = getattr(record, "partition", None)
        if partition is not None:
            offset = f"{partition}-{getattr(record, 'offset', '')}"
        return {
            "record": {
                "key": record.key,
                "value": value,
                "headers": {str(k): str(v) for k, v in record.headers},
            },
            "offset": offset,
        }

    @staticmethod
    def _matches(record: Record, filters: List[Tuple[str, str]]) -> bool:
        return all(str(record.header(k)) == v for k, v in filters)

    async def _consume_loop(
        self, ws, registered, topic: str, filters, position: OffsetPosition
    ) -> None:
        reader = registered.topic_runtime.create_reader(
            {"topic": topic}, position
        )
        await reader.start()
        try:
            while not ws.closed:
                batch = await reader.read(timeout=0.2)
                for record in batch:
                    if self._matches(record, filters):
                        await ws.send_json(self._record_to_json(record))
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            await reader.close()

    def _consume_filters(self, gateway, parameters, principal):
        return self._resolve_headers(
            gateway.consume_options.get("filters", {}).get("headers"),
            parameters,
            principal,
        )

    async def _ws_consume(self, request) -> web.WebSocketResponse:
        try:
            registered, gateway, parameters, options, credentials = self._resolve(
                request, "consume"
            )
            principal = await self._authenticate(gateway, credentials)
        except GatewayError as error:
            raise web.HTTPBadRequest(text=str(error))
        position = OffsetPosition.LATEST
        if options.get("position") == "earliest":
            position = OffsetPosition.EARLIEST
        filters = self._consume_filters(gateway, parameters, principal)
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(registered, gateway, "ClientConnected", parameters)
        consume_task = asyncio.ensure_future(
            self._consume_loop(ws, registered, gateway.topic, filters, position)
        )
        try:
            async for message in ws:
                # client offset acks are accepted and ignored (the reader is
                # positional; reconnect with option:position to replay)
                continue
        finally:
            consume_task.cancel()
            await self._emit_event(registered, gateway, "ClientDisconnected", parameters)
        return ws

    # ------------------------------------------------------------------ #
    # chat (produce + filtered consume on one socket; ChatHandler.java:42)
    # ------------------------------------------------------------------ #
    async def _ws_chat(self, request) -> web.WebSocketResponse:
        try:
            registered, gateway, parameters, _options, credentials = self._resolve(
                request, "chat"
            )
            principal = await self._authenticate(gateway, credentials)
        except GatewayError as error:
            raise web.HTTPBadRequest(text=str(error))
        chat = gateway.chat_options
        questions_topic = chat.get("questions-topic")
        answers_topic = chat.get("answers-topic")
        if not questions_topic or not answers_topic:
            raise web.HTTPBadRequest(
                text="chat gateway requires chat-options.questions-topic and answers-topic"
            )
        headers = self._resolve_headers(chat.get("headers"), parameters, principal)
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(registered, gateway, "ClientConnected", parameters)
        consume_task = asyncio.ensure_future(
            self._consume_loop(
                ws, registered, answers_topic, headers, OffsetPosition.LATEST
            )
        )
        try:
            async for message in ws:
                if message.type != WSMsgType.TEXT:
                    continue
                try:
                    key, value, user_headers = self._parse_produce(message.data)
                    chat_headers, trace_id = self._stamp_trace(
                        tuple(user_headers) + tuple(headers)
                    )
                    with self.tracer.span(
                        "gateway.chat.produce", trace_id=trace_id,
                        gateway=gateway.id, topic=questions_topic,
                    ):
                        await (
                            await registered.producer(questions_topic)
                        ).write(
                            Record(value=value, key=key, headers=chat_headers)
                        )
                    self.metrics.counter("records_produced").count()
                except GatewayError as error:
                    await ws.send_json({"status": "BAD_REQUEST", "reason": str(error)})
        finally:
            consume_task.cancel()
            await self._emit_event(registered, gateway, "ClientDisconnected", parameters)
        return ws

    # ------------------------------------------------------------------ #
    # service gateway (topic round-trip; GatewayResource.java:156-190)
    # ------------------------------------------------------------------ #
    async def _proxy_service(
        self, request, base_url: str, suffix: str = ""
    ) -> web.Response:
        """Forward the request to an agent service endpoint and relay the
        response verbatim (the reference's direct-proxy service mode);
        ``option:path`` selects the upstream path."""
        import aiohttp

        body = await request.read()
        target = base_url.rstrip("/") + (
            "/" + suffix.lstrip("/") if suffix else ""
        )
        headers = {}
        if request.content_type:
            headers["Content-Type"] = request.content_type
        try:
            async with aiohttp.ClientSession() as session:
                async with session.request(
                    request.method, target, data=body or None,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(total=60),
                ) as upstream:
                    payload = await upstream.read()
                    return web.Response(
                        body=payload,
                        status=upstream.status,
                        content_type=upstream.content_type,
                    )
        except aiohttp.ClientError as error:
            return web.json_response(
                {"status": "ERROR", "reason": f"service unreachable: {error}"},
                status=502,
            )

    async def _http_service(self, request) -> web.Response:
        try:
            registered, gateway, parameters, options, credentials = self._resolve(
                request, "service"
            )
            principal = await self._authenticate(gateway, credentials)
        except GatewayError as error:
            return web.json_response(
                {"status": "ERROR", "reason": str(error)}, status=error.status
            )
        service = gateway.service_options
        # direct proxy mode (reference: GatewayResource.java:234,331-345
        # getExecutorServiceURI): forward straight to the agent service
        # pod instead of a topic round trip
        proxy_url = service.get("service-url")
        if not proxy_url and service.get("agent-id"):
            name = (
                f"{registered.application.application_id}-"
                f"{service['agent-id']}"
            )
            tenant = request.match_info["tenant"]
            proxy_url = f"http://{name}.{tenant}.svc:8000"
        if proxy_url:
            return await self._proxy_service(
                request, proxy_url, options.get("path", "")
            )
        input_topic = service.get("input-topic")
        output_topic = service.get("output-topic")
        if not input_topic or not output_topic:
            return web.json_response(
                {"status": "ERROR", "reason": "service gateway needs input/output topics"},
                status=400,
            )
        request_id = uuid.uuid4().hex
        reader = registered.topic_runtime.create_reader(
            {"topic": output_topic}, OffsetPosition.LATEST
        )
        await reader.start()
        key, value, user_headers = self._parse_produce(await request.text())
        service_headers, trace_id = self._stamp_trace(
            tuple(user_headers)
            + (("langstream-service-request-id", request_id),)
        )
        with self.tracer.span(
            "gateway.service.produce", trace_id=trace_id,
            gateway=gateway.id, topic=input_topic,
        ):
            await (await registered.producer(input_topic)).write(
                Record(value=value, key=key, headers=service_headers)
            )
        self.metrics.counter("service_requests").count()
        timeout = float(service.get("timeout-seconds", 30))
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                for record in await reader.read(timeout=0.2):
                    if record.header("langstream-service-request-id") == request_id:
                        return web.json_response(self._record_to_json(record))
        finally:
            await reader.close()
        return web.json_response(
            {"status": "ERROR", "reason": "timed out waiting for the response"},
            status=504,
        )
