"""WebSocket/HTTP gateway — the user-facing ingress to pipeline topics.

Equivalent of the reference's ``langstream-api-gateway`` module (Spring
WebSocket + HTTP): WS ``/v1/{produce,consume,chat}/{tenant}/{app}/{gateway}``
and HTTP ``/api/gateways/...`` including the ``service`` request/response
round-trip. Implemented on aiohttp, sharing the event loop with the local
application runner.
"""

from langstream_tpu.gateway.server import GatewayServer

__all__ = ["GatewayServer"]
