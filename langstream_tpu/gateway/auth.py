"""Gateway authentication providers.

Equivalent of the reference's pluggable gateway auth
(``langstream-api-gateway-auth``: ``github``, ``google``, ``jwt``, generic
``http`` providers loaded by ``GatewayAuthenticationProviderRegistry``).

Providers here:

- ``test``      — accepts any credential (the reference's test-credentials
  path); principal = the credential string.
- ``http``      — POST the credential to a configured endpoint; 2xx = ok,
  JSON body becomes the principal attributes.
- ``jwt``       — HS256 (shared secret, stdlib hmac) and RS256 with either
  a configured PEM public key or a JWKS URI with kid-keyed key cache
  (reference: ``langstream-auth-jwt`` + ``JwksUriSigningKeyResolver.java``);
  claims become principal attributes.
- ``google``    — ID-token validation via the tokeninfo endpoint with an
  audience check.
- ``github``    — access-token validation via the user API.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any, Dict, Optional


class AuthenticationFailed(Exception):
    pass


class Principal:
    def __init__(self, subject: str, attributes: Optional[Dict[str, Any]] = None):
        self.subject = subject
        self.attributes = attributes or {}

    def get(self, field: str) -> Any:
        if field == "subject":
            return self.subject
        return self.attributes.get(field)


class GatewayAuthProvider:
    async def authenticate(self, credentials: str) -> Principal:
        raise NotImplementedError

    async def close(self) -> None:
        ...


class TestAuthProvider(GatewayAuthProvider):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.config = config

    async def authenticate(self, credentials: str) -> Principal:
        return Principal(subject=credentials or "anonymous")


class HttpAuthProvider(GatewayAuthProvider):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.endpoint = config["endpoint"]
        self.method = config.get("method", "POST")
        self._session = None

    async def authenticate(self, credentials: str) -> Principal:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        async with self._session.request(
            self.method,
            self.endpoint,
            headers={"Authorization": f"Bearer {credentials}"},
        ) as response:
            if response.status >= 300:
                raise AuthenticationFailed(f"auth endpoint HTTP {response.status}")
            try:
                attributes = await response.json()
            except Exception:  # noqa: BLE001
                attributes = {}
        if not isinstance(attributes, dict):
            attributes = {}
        return Principal(
            subject=str(attributes.get("subject", attributes.get("sub", "user"))),
            attributes=attributes,
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


def _b64url_decode(data: str) -> bytes:
    padding = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + padding)


class JwtAuthProvider(GatewayAuthProvider):
    """JWT validation (``langstream-auth-jwt`` analogue).

    - ``secret-key``  — HS256 shared secret (stdlib hmac).
    - ``public-key``  — PEM RSA public key for RS256.
    - ``jwks-uri``    — RS256 keys resolved by ``kid`` from a JWKS
      endpoint, cached; an unknown kid triggers one refetch (the
      reference's ``JwksUriSigningKeyResolver.java`` rotation behavior).
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        self.secret = config.get("secret-key", config.get("secret", ""))
        self.public_key_pem = config.get("public-key")
        self.jwks_uri = config.get("jwks-uri") or config.get("jwks-hosts") \
            or config.get("jwksUri")
        if not (self.secret or self.public_key_pem or self.jwks_uri):
            raise ValueError(
                "jwt auth requires 'secret-key' (HS256), 'public-key' "
                "(RS256 PEM), or 'jwks-uri' (RS256 JWKS)"
            )
        self.audience = config.get("audience")
        self.verify_expiry = bool(config.get("verify-expiry", True))
        self._jwks_keys: Dict[str, Any] = {}  # kid -> public key object
        # rotation: the cache expires so rotated-OUT keys stop being
        # trusted; unknown-kid refetches are throttled so unauthenticated
        # garbage tokens can't amplify into JWKS traffic
        self.jwks_refresh = float(config.get("jwks-refresh-seconds", 300))
        self._jwks_fetched_at = 0.0
        self._jwks_min_fetch_interval = 30.0

    # -- RS256 key material --------------------------------------------- #
    def _pem_key(self):
        from cryptography.hazmat.primitives.serialization import (
            load_pem_public_key,
        )

        return load_pem_public_key(self.public_key_pem.encode())

    async def _jwks_key(self, kid: Optional[str]):
        now = time.time()
        fresh = now - self._jwks_fetched_at < self.jwks_refresh
        if kid in self._jwks_keys and fresh:
            return self._jwks_keys[kid]
        throttled = (
            now - self._jwks_fetched_at < self._jwks_min_fetch_interval
        )
        if throttled:
            # recently refetched: trust the current document only
            if kid in self._jwks_keys:
                return self._jwks_keys[kid]
            raise AuthenticationFailed(f"no JWKS key for kid {kid!r}")
        import aiohttp
        from cryptography.hazmat.primitives.asymmetric import rsa

        async with aiohttp.ClientSession() as session:
            async with session.get(self.jwks_uri) as response:
                if response.status >= 300:
                    raise AuthenticationFailed(
                        f"JWKS fetch HTTP {response.status}"
                    )
                document = await response.json(content_type=None)
        # REPLACE the cache: rotated-out keys must stop being trusted
        keys: Dict[str, Any] = {}
        for jwk in document.get("keys", []):
            if jwk.get("kty") != "RSA":
                continue
            n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
            e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
            keys[jwk.get("kid")] = rsa.RSAPublicNumbers(e, n).public_key()
        self._jwks_keys = keys
        self._jwks_fetched_at = now
        if kid not in self._jwks_keys:
            if None in self._jwks_keys and kid is None:
                return self._jwks_keys[None]
            raise AuthenticationFailed(f"no JWKS key for kid {kid!r}")
        return self._jwks_keys[kid]

    def _verify_rs256(self, key, signing_input: bytes, signature: bytes):
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        try:
            key.verify(
                signature, signing_input, padding.PKCS1v15(), hashes.SHA256()
            )
        except InvalidSignature as error:
            raise AuthenticationFailed("bad JWT signature") from error

    async def authenticate(self, credentials: str) -> Principal:
        try:
            header_b64, payload_b64, signature_b64 = credentials.split(".")
        except ValueError as error:
            raise AuthenticationFailed("malformed JWT") from error
        header = json.loads(_b64url_decode(header_b64))
        alg = header.get("alg")
        signing_input = f"{header_b64}.{payload_b64}".encode()
        signature = _b64url_decode(signature_b64)
        if alg == "HS256":
            if not self.secret:
                raise AuthenticationFailed("HS256 token but no secret-key")
            expected = hmac.new(
                self.secret.encode(), signing_input, hashlib.sha256
            ).digest()
            if not hmac.compare_digest(expected, signature):
                raise AuthenticationFailed("bad JWT signature")
        elif alg == "RS256":
            if self.public_key_pem:
                key = self._pem_key()
            elif self.jwks_uri:
                key = await self._jwks_key(header.get("kid"))
            else:
                raise AuthenticationFailed(
                    "RS256 token but no public-key/jwks-uri configured"
                )
            self._verify_rs256(key, signing_input, signature)
        else:
            raise AuthenticationFailed(f"unsupported JWT alg {alg!r}")
        claims = json.loads(_b64url_decode(payload_b64))
        if self.verify_expiry and "exp" in claims and claims["exp"] < time.time():
            raise AuthenticationFailed("JWT expired")
        if self.audience and claims.get("aud") != self.audience:
            raise AuthenticationFailed("JWT audience mismatch")
        return Principal(subject=str(claims.get("sub", "user")), attributes=claims)


# backward-compatible alias (pre-RS256 name)
JwtHS256AuthProvider = JwtAuthProvider


class GoogleAuthProvider(GatewayAuthProvider):
    """Google ID-token validation via the tokeninfo endpoint (reference:
    ``langstream-api-gateway-auth/.../GoogleAuthenticationProvider``).
    Config: ``clientId`` (audience check); ``tokeninfo-url`` override for
    tests/self-hosted validators."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.client_id = config.get("clientId") or config.get("client-id")
        self.tokeninfo_url = config.get(
            "tokeninfo-url", "https://oauth2.googleapis.com/tokeninfo"
        )

    async def authenticate(self, credentials: str) -> Principal:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.get(
                self.tokeninfo_url, params={"id_token": credentials}
            ) as response:
                # status first: a proxy 502 with an HTML body must fail
                # as AuthenticationFailed, not a JSON decode traceback
                if response.status >= 300:
                    raise AuthenticationFailed(
                        f"google tokeninfo HTTP {response.status}"
                    )
                payload = await response.json(content_type=None)
        if self.client_id and payload.get("aud") != self.client_id:
            raise AuthenticationFailed("google token audience mismatch")
        # tokeninfo always reports the issuer; Google's own verifier
        # accepts exactly these two spellings (GoogleIdTokenVerifier
        # semantics — the reference delegates to it). A payload WITHOUT
        # iss is not a genuine tokeninfo response — fail closed.
        if payload.get("iss") not in (
            "accounts.google.com", "https://accounts.google.com"
        ):
            raise AuthenticationFailed(
                f"google token issuer {payload.get('iss')!r} "
                "not accounts.google.com"
            )
        if "exp" in payload and float(payload["exp"]) < time.time():
            raise AuthenticationFailed("google token expired")
        subject = payload.get("email") or payload.get("sub")
        if not subject:
            raise AuthenticationFailed("google token has no subject")
        return Principal(subject=str(subject), attributes=payload)


class GithubAuthProvider(GatewayAuthProvider):
    """GitHub access-token validation via the user API (reference:
    ``GitHubAuthenticationProvider``). Config: ``api-url`` override for
    tests/GHE."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.api_url = config.get(
            "api-url", "https://api.github.com"
        ).rstrip("/")

    async def authenticate(self, credentials: str) -> Principal:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{self.api_url}/user",
                headers={
                    "Authorization": f"Bearer {credentials}",
                    "Accept": "application/vnd.github+json",
                },
            ) as response:
                if response.status >= 300:
                    raise AuthenticationFailed(
                        f"github user API HTTP {response.status}"
                    )
                payload = await response.json(content_type=None)
        login = payload.get("login")
        if not login:
            raise AuthenticationFailed("github token has no login")
        return Principal(subject=str(login), attributes=payload)


def create_auth_provider(config: Dict[str, Any]) -> GatewayAuthProvider:
    provider = config.get("provider", "test")
    configuration = config.get("configuration", {}) or {}
    if provider == "test":
        return TestAuthProvider(configuration)
    if provider == "http":
        return HttpAuthProvider(configuration)
    if provider == "jwt":
        return JwtAuthProvider(configuration)
    if provider == "google":
        return GoogleAuthProvider(configuration)
    if provider == "github":
        return GithubAuthProvider(configuration)
    raise ValueError(f"unknown auth provider {provider!r}")
