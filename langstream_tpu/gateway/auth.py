"""Gateway authentication providers.

Equivalent of the reference's pluggable gateway auth
(``langstream-api-gateway-auth``: ``github``, ``google``, ``jwt``, generic
``http`` providers loaded by ``GatewayAuthenticationProviderRegistry``).

Providers here:

- ``test``      — accepts any credential (the reference's test-credentials
  path); principal = the credential string.
- ``http``      — POST the credential to a configured endpoint; 2xx = ok,
  JSON body becomes the principal attributes.
- ``jwt``       — HS256 verification with a shared secret, implemented on
  stdlib hmac (no external JWT lib); claims become principal attributes.
  RS256/JWKS (the reference's Kubernetes JWKS path) is gated until a
  crypto dependency is available.
- ``google`` / ``github`` — gated: they need outbound calls to the identity
  provider; configs validate but authentication fails with a clear error.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any, Dict, Optional


class AuthenticationFailed(Exception):
    pass


class Principal:
    def __init__(self, subject: str, attributes: Optional[Dict[str, Any]] = None):
        self.subject = subject
        self.attributes = attributes or {}

    def get(self, field: str) -> Any:
        if field == "subject":
            return self.subject
        return self.attributes.get(field)


class GatewayAuthProvider:
    async def authenticate(self, credentials: str) -> Principal:
        raise NotImplementedError

    async def close(self) -> None:
        ...


class TestAuthProvider(GatewayAuthProvider):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.config = config

    async def authenticate(self, credentials: str) -> Principal:
        return Principal(subject=credentials or "anonymous")


class HttpAuthProvider(GatewayAuthProvider):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.endpoint = config["endpoint"]
        self.method = config.get("method", "POST")
        self._session = None

    async def authenticate(self, credentials: str) -> Principal:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        async with self._session.request(
            self.method,
            self.endpoint,
            headers={"Authorization": f"Bearer {credentials}"},
        ) as response:
            if response.status >= 300:
                raise AuthenticationFailed(f"auth endpoint HTTP {response.status}")
            try:
                attributes = await response.json()
            except Exception:  # noqa: BLE001
                attributes = {}
        if not isinstance(attributes, dict):
            attributes = {}
        return Principal(
            subject=str(attributes.get("subject", attributes.get("sub", "user"))),
            attributes=attributes,
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


def _b64url_decode(data: str) -> bytes:
    padding = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + padding)


class JwtHS256AuthProvider(GatewayAuthProvider):
    """HS256 JWT validation on stdlib hmac (``langstream-auth-jwt``
    analogue for shared-secret deployments)."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.secret = config.get("secret-key", config.get("secret", ""))
        if not self.secret:
            raise ValueError("jwt auth requires 'secret-key'")
        self.audience = config.get("audience")
        self.verify_expiry = bool(config.get("verify-expiry", True))

    async def authenticate(self, credentials: str) -> Principal:
        try:
            header_b64, payload_b64, signature_b64 = credentials.split(".")
        except ValueError as error:
            raise AuthenticationFailed("malformed JWT") from error
        header = json.loads(_b64url_decode(header_b64))
        if header.get("alg") != "HS256":
            raise AuthenticationFailed(
                f"unsupported JWT alg {header.get('alg')!r} (only HS256 in "
                "this build; RS256/JWKS requires a crypto dependency)"
            )
        expected = hmac.new(
            self.secret.encode(),
            f"{header_b64}.{payload_b64}".encode(),
            hashlib.sha256,
        ).digest()
        if not hmac.compare_digest(expected, _b64url_decode(signature_b64)):
            raise AuthenticationFailed("bad JWT signature")
        claims = json.loads(_b64url_decode(payload_b64))
        if self.verify_expiry and "exp" in claims and claims["exp"] < time.time():
            raise AuthenticationFailed("JWT expired")
        if self.audience and claims.get("aud") != self.audience:
            raise AuthenticationFailed("JWT audience mismatch")
        return Principal(subject=str(claims.get("sub", "user")), attributes=claims)


class GatedAuthProvider(GatewayAuthProvider):
    def __init__(self, name: str) -> None:
        self.name = name

    async def authenticate(self, credentials: str) -> Principal:
        raise AuthenticationFailed(
            f"auth provider {self.name!r} requires outbound identity-provider "
            "access not available in this build; use 'jwt' or 'http'"
        )


def create_auth_provider(config: Dict[str, Any]) -> GatewayAuthProvider:
    provider = config.get("provider", "test")
    configuration = config.get("configuration", {}) or {}
    if provider == "test":
        return TestAuthProvider(configuration)
    if provider == "http":
        return HttpAuthProvider(configuration)
    if provider == "jwt":
        return JwtHS256AuthProvider(configuration)
    if provider in ("google", "github"):
        return GatedAuthProvider(provider)
    raise ValueError(f"unknown auth provider {provider!r}")
