"""Local application UI (reference: ``langstream-cli/.../applications/
UIAppCmd.java`` — ``langstream apps ui`` serves a small page for poking
an app's gateways). One static page + one JSON describe endpoint; all
data flows through the same public WS gateways a real client uses.
"""

from __future__ import annotations

from typing import Any, Dict

PAGE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>langstream-tpu — __APP__</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 2rem; max-width: 60rem; }
  h1 { font-size: 1.2rem; }
  fieldset { margin: 1rem 0; border: 1px solid #999; border-radius: 4px; }
  textarea, input[type=text] { width: 100%; box-sizing: border-box; }
  #log { background: #111; color: #ddd; padding: .75rem; height: 18rem;
         overflow-y: auto; font-family: monospace; font-size: .85rem;
         white-space: pre-wrap; }
  .meta { color: #666; font-size: .85rem; }
  button { margin-top: .4rem; }
</style>
</head>
<body>
<h1>langstream-tpu · <code>__TENANT__/__APP__</code></h1>
<div class="meta">gateways: <span id="gateways"></span></div>

<fieldset>
  <legend>chat</legend>
  <label>gateway <select id="chat-gateway"></select></label>
  <input type="text" id="chat-input" placeholder="type a message, press Enter">
</fieldset>

<fieldset>
  <legend>produce</legend>
  <label>gateway <select id="produce-gateway"></select></label>
  <textarea id="produce-value" rows="2" placeholder="record value"></textarea>
  <button onclick="produce()">send</button>
</fieldset>

<fieldset>
  <legend>consume</legend>
  <label>gateway <select id="consume-gateway"></select></label>
  <button onclick="consume()">attach</button>
</fieldset>

<div id="log"></div>

<script>
const tenant = "__TENANT__", app = "__APP__";
const base = `ws://${location.host}/v1`;
const log = (line) => {
  const el = document.getElementById("log");
  el.textContent += line + "\\n";
  el.scrollTop = el.scrollHeight;
};
let chatWs = null, consumeWs = null;
const session = Math.random().toString(36).slice(2);

fetch(`/ui/api/${tenant}/${app}`).then(r => r.json()).then(info => {
  document.getElementById("gateways").textContent =
    info.gateways.map(g => `${g.id} (${g.type})`).join(", ") || "none";
  for (const g of info.gateways) {
    const sel = document.getElementById(`${g.type}-gateway`);
    if (sel) sel.add(new Option(g.id, g.id));
  }
});

function wsUrl(kind, gateway) {
  return `${base}/${kind}/${tenant}/${app}/${gateway}` +
         `?param:session-id=${session}&param:sessionId=${session}`;
}

document.getElementById("chat-input").addEventListener("keydown", (e) => {
  if (e.key !== "Enter") return;
  const gateway = document.getElementById("chat-gateway").value;
  if (!gateway) { log("! no chat gateway"); return; }
  const value = e.target.value;
  e.target.value = "";
  const send = () => { log(`> ${value}`); chatWs.send(JSON.stringify({value})); };
  if (!chatWs || chatWs.readyState !== 1) {
    chatWs = new WebSocket(wsUrl("chat", gateway));
    let acc = "";
    chatWs.onmessage = (m) => {
      const doc = JSON.parse(m.data);
      const rec = doc.record || {};
      const headers = rec.headers || {};
      if (headers["stream-last-message"] === "true") {
        log(`< ${acc + (rec.value || "")}`); acc = "";
      } else if (headers["stream-index"]) {
        acc += rec.value || "";
      } else {
        log(`< ${rec.value}`);
      }
    };
    chatWs.onopen = send;
    chatWs.onerror = () => log("! chat socket error");
  } else { send(); }
});

function produce() {
  const gateway = document.getElementById("produce-gateway").value;
  if (!gateway) { log("! no produce gateway"); return; }
  const value = document.getElementById("produce-value").value;
  const ws = new WebSocket(wsUrl("produce", gateway));
  ws.onopen = () => ws.send(JSON.stringify({value}));
  ws.onmessage = (m) => { log(`produce ack: ${m.data}`); ws.close(); };
}

function consume() {
  const gateway = document.getElementById("consume-gateway").value;
  if (!gateway) { log("! no consume gateway"); return; }
  if (consumeWs) consumeWs.close();
  consumeWs = new WebSocket(wsUrl("consume", gateway));
  consumeWs.onmessage = (m) => {
    const rec = (JSON.parse(m.data).record || {});
    log(`[${gateway}] ${JSON.stringify(rec.value)}`);
  };
  log(`attached to ${gateway}`);
}
</script>
</body>
</html>
"""


def render_page(tenant: str, application_id: str) -> str:
    return (
        PAGE.replace("__TENANT__", tenant)
        .replace("__APP__", application_id)
    )


def describe(application) -> Dict[str, Any]:
    return {
        "application-id": application.application_id,
        "gateways": [
            {"id": g.id, "type": g.type, "topic": g.topic}
            for g in application.gateways
        ],
    }
